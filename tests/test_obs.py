"""Invocation telemetry (kafkabalancer_tpu/obs): tracer semantics, the
thread-safe registry, and the CLI's -stats/-metrics-json/-trace trio.

The load-bearing pins:

- cross-thread span parenting (the warmup/prefetch overlap engineered in
  the cold-path PR must be VISIBLE, attributed to its background thread);
- the metrics-JSON schema (golden file, versioned — the outer automation
  loop and bench.py consume this instead of scraping stdout);
- Perfetto/Chrome trace validity (JSON loads, monotonic ts, pid/tid
  tracks, thread-name metadata);
- disabled-path behavior: with the trio off nothing is written, and
  error exits still never import jax EVEN WITH the trio on (obs/ is
  jax-free by construction);
- exporters fire on the exit-3/exit-4 error paths.
"""

import gzip
import io
import json
import os
import subprocess
import sys
import threading

import pytest

from kafkabalancer_tpu import obs
from kafkabalancer_tpu.obs import export as obs_export
from kafkabalancer_tpu.obs.metrics import SCHEMA, MetricsRegistry
from kafkabalancer_tpu.obs.trace import NOOP_SPAN, Tracer

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")
GOLDEN = os.path.join(
    os.path.dirname(__file__), "data", "metrics_schema_v1.json"
)


def run_cli(args, stdin=""):
    from kafkabalancer_tpu.cli import run

    out, err = io.StringIO(), io.StringIO()
    rv = run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


# --- tracer semantics -----------------------------------------------------


def test_span_nesting_records_parents():
    tr = Tracer()
    tr.reset(enabled=True)
    with tr.span("a"):
        with tr.span("b"):
            with tr.span("c"):
                pass
        with tr.span("d"):
            pass
    spans = {s["name"]: s for s in tr.snapshot()}
    assert spans["a"]["parent"] is None
    assert spans["b"]["parent"] == spans["a"]["sid"]
    assert spans["c"]["parent"] == spans["b"]["sid"]
    assert spans["d"]["parent"] == spans["a"]["sid"]
    assert all(s["done"] for s in spans.values())
    assert all(s["dur_us"] >= 0 for s in spans.values())


def test_cross_thread_parenting():
    """The CLI pattern: the spawner hands its launch span to the thread
    body; the child's spans land on the child's tid but parent to it."""
    tr = Tracer()
    tr.reset(enabled=True)
    with tr.span("launch") as parent:

        def body():
            with tr.span("worker", parent=parent):
                with tr.span("inner"):
                    pass

        t = threading.Thread(target=body, name="warm-thread")
        t.start()
        t.join()
    spans = {s["name"]: s for s in tr.snapshot()}
    assert spans["worker"]["parent"] == spans["launch"]["sid"]
    # nesting INSIDE the thread needs no explicit parent
    assert spans["inner"]["parent"] == spans["worker"]["sid"]
    assert spans["worker"]["tid"] != spans["launch"]["tid"]
    assert spans["worker"]["thread"] == "warm-thread"


def test_in_flight_spans_export_as_unfinished():
    tr = Tracer()
    tr.reset(enabled=True)
    started = threading.Event()
    release = threading.Event()

    def body():
        with tr.span("bg"):
            started.set()
            release.wait(30.0)

    t = threading.Thread(target=body)
    t.start()
    assert started.wait(30.0)
    snap = {s["name"]: s for s in tr.snapshot()}
    assert snap["bg"]["done"] is False
    release.set()
    t.join(30.0)
    snap = {s["name"]: s for s in tr.snapshot()}
    assert snap["bg"]["done"] is True


def test_disabled_tracer_is_noop_fast_path():
    tr = Tracer()  # disabled by default
    s = tr.span("x")
    assert s is NOOP_SPAN  # one shared singleton, nothing allocated
    with s:
        with tr.span("y"):
            pass
    assert tr.snapshot() == []
    assert tr.current() is None


def test_snapshot_timestamps_monotone_in_record_order():
    tr = Tracer()
    tr.reset(enabled=True)
    for i in range(20):
        with tr.span(f"s{i}"):
            pass
    ts = [s["start_us"] for s in tr.snapshot()]
    assert ts == sorted(ts)


# --- registry -------------------------------------------------------------


def test_registry_concurrent_mutation():
    """The satellite pin: the old aot.stats was a bare dict setdefault'd
    from two threads; the registry must absorb concurrent writers."""
    reg = MetricsRegistry()

    def body(k):
        for i in range(1000):
            reg.count("n")
            reg.phase_set(f"g{k}", "v", float(i))
            reg.event("e", k=k) if i % 100 == 0 else None

    threads = [threading.Thread(target=body, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["n"] == 8000
    assert len(snap["phases"]) == 8


def test_registry_event_cap_counts_drops():
    from kafkabalancer_tpu.obs.metrics import _MAX_EVENTS

    reg = MetricsRegistry()
    for _ in range(_MAX_EVENTS + 10):
        reg.event("x")
    snap = reg.snapshot()
    assert len(snap["events"]) == _MAX_EVENTS
    assert snap["events_dropped"] == 10


def test_aot_stats_alias_is_readonly_registry_view():
    """ops.aot.stats survives as a read-only Mapping over the registry's
    phase groups: lookups see registry writes, item assignment is gone,
    clear() is the between-measurements reset the tests/bench idiom
    needs."""
    from kafkabalancer_tpu.ops import aot

    obs.metrics.reset()
    obs.metrics.phase_set("score_window", "prefetch", 1.0)
    assert aot.stats["score_window"].get("prefetch") == 1.0
    assert "score_window" in aot.stats
    assert aot.stats.get("missing", {}) == {}
    with pytest.raises(TypeError):
        aot.stats["score_window"] = {}  # read-only: no item assignment
    # lookups return copies — mutating one never writes through
    view = aot.stats["score_window"]
    view["prefetch"] = 99.0
    assert aot.stats["score_window"]["prefetch"] == 1.0
    aot.stats.clear()
    assert "score_window" not in aot.stats


# --- CLI flag trio --------------------------------------------------------


def test_metrics_json_schema_golden(tmp_path):
    """Golden-file pin: the payload's top-level keys, span keys, and the
    schema string are VERSIONED — changing any of them must come with a
    schema bump and a new golden."""
    mpath = tmp_path / "m.json"
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, f"-metrics-json={mpath}"]
    )
    assert rv == 0, err
    raw = mpath.read_text()
    assert raw.endswith("\n") and "\n" not in raw[:-1]  # single line
    payload = json.loads(raw)
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert payload["schema"] == golden["schema"] == SCHEMA
    assert sorted(payload) == sorted(golden["top_level_keys"])
    for sp in payload["spans"]:
        base = set(golden["span_keys"])
        assert base <= set(sp) <= base | {"attrs"}
    for ev in payload["events"]:
        assert set(golden["event_base_keys"]) <= set(ev)
    names = {s["name"] for s in payload["spans"]}
    assert {"validate_flags", "parse_input", "plan", "emit"} <= names
    assert payload["rc"] == 0
    assert payload["counters"]["cli.changes_written"] >= 1


def test_metrics_json_dash_is_last_stdout_line():
    rv, out, _err = run_cli(
        ["-input-json", "-input", FIXTURE, "-metrics-json=-"]
    )
    assert rv == 0
    lines = out.strip().splitlines()
    assert lines[0].startswith('{"version"')  # the plan comes first
    payload = json.loads(lines[-1])
    assert payload["schema"] == SCHEMA


def test_trace_file_is_valid_chrome_trace(tmp_path):
    tpath = tmp_path / "t.json"
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, f"-trace={tpath}"]
    )
    assert rv == 0, err
    with open(tpath) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    xs = [ev for ev in evs if ev["ph"] == "X"]
    assert xs
    for ev in xs:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(ev)
        assert ev["ts"] >= 0 and ev["dur"] >= 0
        assert ev["pid"] == os.getpid()
    ts = [ev["ts"] for ev in xs]
    assert ts == sorted(ts)  # recorded under one lock: start-ordered
    # every tid carries a thread_name metadata track
    tids = {ev["tid"] for ev in xs}
    named = {
        ev["tid"]
        for ev in evs
        if ev["ph"] == "M" and ev["name"] == "thread_name"
    }
    assert tids <= named


def test_stats_summary_goes_to_stderr():
    rv, _out, err = run_cli(["-input-json", "-input", FIXTURE, "-stats"])
    assert rv == 0
    assert "invocation telemetry" in err
    assert "parse_input" in err and "emit" in err
    assert "rc=0" in err


def test_disabled_trio_writes_no_files(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rv, _out, _err = run_cli(["-input-json", "-input", FIXTURE])
    assert rv == 0
    assert os.listdir(tmp_path) == []


def test_exit3_error_path_still_exports(tmp_path):
    mpath = tmp_path / "m.json"
    rv, _out, _err = run_cli(
        ["-input-json", "-max-reassign=-1", f"-metrics-json={mpath}"]
    )
    assert rv == 3
    payload = json.loads(mpath.read_text())
    assert payload["rc"] == 3 and payload["schema"] == SCHEMA
    # the lifecycle got as far as flag validation — and said so
    assert "validate_flags" in {s["name"] for s in payload["spans"]}


def test_exit4_error_path_still_exports(tmp_path):
    class Boom(io.StringIO):
        def write(self, s):
            raise OSError("sink failed")

    from kafkabalancer_tpu.cli import run

    mpath = tmp_path / "m.json"
    with open(FIXTURE) as f:
        src = f.read()
    rv = run(
        io.StringIO(src), Boom(), io.StringIO(),
        ["kafkabalancer", "-input-json", f"-metrics-json={mpath}"],
    )
    assert rv == 4
    payload = json.loads(mpath.read_text())
    assert payload["rc"] == 4
    assert "emit" in {s["name"] for s in payload["spans"]}


def test_flag_error_exit_with_trio_never_imports_jax(tmp_path):
    """The cold-path guarantee (tests/test_coldstart.py) must survive
    the full telemetry trio: obs/ is jax-free, so an argument-error exit
    with -stats -metrics-json -trace all enabled still exits 3 without
    touching jax — and still exports."""
    mpath = str(tmp_path / "m.json")
    tpath = str(tmp_path / "t.json")
    code = (
        "import io, sys\n"
        "from kafkabalancer_tpu.cli import run\n"
        "rc = run(io.StringIO(''), io.StringIO(), io.StringIO(),\n"
        "         ['kafkabalancer', '-input-json', '-solver=tpu',\n"
        f"          '-max-reassign=-1', '-stats', '-metrics-json={mpath}',\n"
        f"          '-trace={tpath}'])\n"
        "assert rc == 3, rc\n"
        "bad = [m for m in sys.modules if m == 'jax' or m.startswith('jax.')]\n"
        "assert not bad, f'jax imported on an error exit: {bad[:3]}'\n"
        "assert 'kafkabalancer_tpu.solvers.scan' not in sys.modules\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert json.loads(open(mpath).read())["rc"] == 3
    assert json.load(open(tpath))["traceEvents"]


def test_fused_lifecycle_spans_cover_background_warmup(tmp_path, monkeypatch):
    """Acceptance pin: a -fused run's metrics JSON carries the lifecycle
    — parse, the warmup on its own BACKGROUND thread (parented to the
    launch site), the session dispatch, and emit."""
    monkeypatch.setenv("KAFKABALANCER_TPU_NO_AOT", "1")
    mpath = tmp_path / "m.json"
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, "-fused", "-fused-batch=4",
         "-max-reassign=4", f"-metrics-json={mpath}"]
    )
    assert rv == 0, err
    payload = json.loads(mpath.read_text())
    spans = payload["spans"]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    names = set(by_name)
    assert {
        "parse_input", "warm_thread_launch", "plan",
        "solver.dispatch_chunk", "tensorize", "emit",
    } <= names, sorted(names)
    launch = by_name["warm_thread_launch"][0]
    warm = by_name["coldstart.warm"][0]
    assert warm["thread"] != launch["thread"]  # its own thread track...
    assert warm["parent"] == launch["sid"]  # ...linked to the launch site
    # the fused dispatch is nested under the plan span
    plan_sids = {s["sid"] for s in by_name["plan"]}
    assert by_name["solver.dispatch_chunk"][0]["parent"] in plan_sids
    # and the session counters made it into the registry
    assert payload["counters"]["solver.chunks"] >= 1
    assert payload["counters"]["solver.moves_committed"] >= 1


# --- -pprof-path satellite ------------------------------------------------


def test_pprof_path_flag_redirects_profile(tmp_path):
    p = tmp_path / "prof.pb.gz"
    rv, _out, _err = run_cli(
        ["-input-json", "-input", FIXTURE, "-pprof", f"-pprof-path={p}"]
    )
    assert rv == 0
    assert gzip.open(p, "rb").read()  # gzipped profile.proto, non-empty


def test_pprof_default_path_unchanged(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rv, _out, _err = run_cli(["-input-json", "-input", FIXTURE, "-pprof"])
    assert rv == 0
    assert (tmp_path / "cpu.pprof").exists()


def test_pprof_write_failure_logged_not_fatal(tmp_path):
    bad = tmp_path / "no-such-dir" / "cpu.pprof"
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, "-pprof", f"-pprof-path={bad}"]
    )
    assert rv == 0  # the plan must not fail on a profile-write failure
    assert "failed writing cpu profile" in err


def test_shared_registry_mode_keeps_stores_and_refcounts_tracing():
    """Multi-lane serving mode: begin_invocation keeps the
    daemon-lifetime registry (no reset), and the tracer drops back to
    the no-op fast path when the LAST tracing request finishes."""
    from kafkabalancer_tpu import obs

    obs.begin_invocation()  # clean slate (unshared reset)
    obs.set_shared_registry(True)
    try:
        obs.metrics.count("x.requests")
        obs.begin_invocation()  # shared: must NOT reset
        assert obs.REGISTRY.counter_get("x.requests") == 1.0

        assert not obs.tracer.enabled
        obs.enable_tracing()  # request A (-stats)
        obs.enable_tracing()  # request B (-metrics-json), concurrent
        assert obs.tracer.enabled
        obs.end_invocation()  # A finishes: B still tracing
        assert obs.tracer.enabled
        obs.end_invocation()  # B finishes: back to the no-op fast path
        assert not obs.tracer.enabled
        # recorded spans survive the disable (trim owns the bound)
        obs.end_invocation()  # over-release is harmless
        assert not obs.tracer.enabled
    finally:
        obs.set_shared_registry(False)
        obs.begin_invocation()


def test_tracer_trim_keeps_inflight_and_newest_spans():
    from kafkabalancer_tpu.obs.trace import Tracer

    tr = Tracer()
    tr.enable()
    open_span = tr.span("inflight")
    open_span.__enter__()
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    tr.trim(cap=3)
    names = [s["name"] for s in tr.snapshot()]
    assert "inflight" in names  # in-flight spans are never dropped
    assert len(names) == 3
    assert names[-1] == "s9"  # oldest completed dropped first
    open_span.__exit__(None, None, None)
