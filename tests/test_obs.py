"""Invocation telemetry (kafkabalancer_tpu/obs): tracer semantics, the
thread-safe registry, and the CLI's -stats/-metrics-json/-trace trio.

The load-bearing pins:

- cross-thread span parenting (the warmup/prefetch overlap engineered in
  the cold-path PR must be VISIBLE, attributed to its background thread);
- the metrics-JSON schema (golden file, versioned — the outer automation
  loop and bench.py consume this instead of scraping stdout);
- Perfetto/Chrome trace validity (JSON loads, monotonic ts, pid/tid
  tracks, thread-name metadata);
- disabled-path behavior: with the trio off nothing is written, and
  error exits still never import jax EVEN WITH the trio on (obs/ is
  jax-free by construction);
- exporters fire on the exit-3/exit-4 error paths.
"""

import gzip
import io
import json
import os
import subprocess
import sys
import threading

import pytest

from kafkabalancer_tpu import obs
from kafkabalancer_tpu.obs import export as obs_export
from kafkabalancer_tpu.obs.metrics import SCHEMA, MetricsRegistry
from kafkabalancer_tpu.obs.trace import NOOP_SPAN, Tracer

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")
GOLDEN = os.path.join(
    os.path.dirname(__file__), "data", "metrics_schema_v1.json"
)


def run_cli(args, stdin=""):
    from kafkabalancer_tpu.cli import run

    out, err = io.StringIO(), io.StringIO()
    rv = run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


# --- tracer semantics -----------------------------------------------------


def test_span_nesting_records_parents():
    tr = Tracer()
    tr.reset(enabled=True)
    with tr.span("a"):
        with tr.span("b"):
            with tr.span("c"):
                pass
        with tr.span("d"):
            pass
    spans = {s["name"]: s for s in tr.snapshot()}
    assert spans["a"]["parent"] is None
    assert spans["b"]["parent"] == spans["a"]["sid"]
    assert spans["c"]["parent"] == spans["b"]["sid"]
    assert spans["d"]["parent"] == spans["a"]["sid"]
    assert all(s["done"] for s in spans.values())
    assert all(s["dur_us"] >= 0 for s in spans.values())


def test_cross_thread_parenting():
    """The CLI pattern: the spawner hands its launch span to the thread
    body; the child's spans land on the child's tid but parent to it."""
    tr = Tracer()
    tr.reset(enabled=True)
    with tr.span("launch") as parent:

        def body():
            with tr.span("worker", parent=parent):
                with tr.span("inner"):
                    pass

        t = threading.Thread(target=body, name="warm-thread")
        t.start()
        t.join()
    spans = {s["name"]: s for s in tr.snapshot()}
    assert spans["worker"]["parent"] == spans["launch"]["sid"]
    # nesting INSIDE the thread needs no explicit parent
    assert spans["inner"]["parent"] == spans["worker"]["sid"]
    assert spans["worker"]["tid"] != spans["launch"]["tid"]
    assert spans["worker"]["thread"] == "warm-thread"


def test_in_flight_spans_export_as_unfinished():
    tr = Tracer()
    tr.reset(enabled=True)
    started = threading.Event()
    release = threading.Event()

    def body():
        with tr.span("bg"):
            started.set()
            release.wait(30.0)

    t = threading.Thread(target=body)
    t.start()
    assert started.wait(30.0)
    snap = {s["name"]: s for s in tr.snapshot()}
    assert snap["bg"]["done"] is False
    release.set()
    t.join(30.0)
    snap = {s["name"]: s for s in tr.snapshot()}
    assert snap["bg"]["done"] is True


def test_disabled_tracer_is_noop_fast_path():
    tr = Tracer()  # disabled by default
    s = tr.span("x")
    assert s is NOOP_SPAN  # one shared singleton, nothing allocated
    with s:
        with tr.span("y"):
            pass
    assert tr.snapshot() == []
    assert tr.current() is None


def test_snapshot_timestamps_monotone_in_record_order():
    tr = Tracer()
    tr.reset(enabled=True)
    for i in range(20):
        with tr.span(f"s{i}"):
            pass
    ts = [s["start_us"] for s in tr.snapshot()]
    assert ts == sorted(ts)


# --- registry -------------------------------------------------------------


def test_registry_concurrent_mutation():
    """The satellite pin: the old aot.stats was a bare dict setdefault'd
    from two threads; the registry must absorb concurrent writers."""
    reg = MetricsRegistry()

    def body(k):
        for i in range(1000):
            reg.count("n")
            reg.phase_set(f"g{k}", "v", float(i))
            reg.event("e", k=k) if i % 100 == 0 else None

    threads = [threading.Thread(target=body, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["n"] == 8000
    assert len(snap["phases"]) == 8


def test_registry_event_cap_counts_drops():
    from kafkabalancer_tpu.obs.metrics import _MAX_EVENTS

    reg = MetricsRegistry()
    for _ in range(_MAX_EVENTS + 10):
        reg.event("x")
    snap = reg.snapshot()
    assert len(snap["events"]) == _MAX_EVENTS
    assert snap["events_dropped"] == 10


def test_aot_stats_alias_is_readonly_registry_view():
    """ops.aot.stats survives as a read-only Mapping over the registry's
    phase groups: lookups see registry writes, item assignment is gone,
    clear() is the between-measurements reset the tests/bench idiom
    needs."""
    from kafkabalancer_tpu.ops import aot

    obs.metrics.reset()
    obs.metrics.phase_set("score_window", "prefetch", 1.0)
    assert aot.stats["score_window"].get("prefetch") == 1.0
    assert "score_window" in aot.stats
    assert aot.stats.get("missing", {}) == {}
    with pytest.raises(TypeError):
        aot.stats["score_window"] = {}  # read-only: no item assignment
    # lookups return copies — mutating one never writes through
    view = aot.stats["score_window"]
    view["prefetch"] = 99.0
    assert aot.stats["score_window"]["prefetch"] == 1.0
    aot.stats.clear()
    assert "score_window" not in aot.stats


# --- CLI flag trio --------------------------------------------------------


def test_metrics_json_schema_golden(tmp_path):
    """Golden-file pin: the payload's top-level keys, span keys, and the
    schema string are VERSIONED — changing any of them must come with a
    schema bump and a new golden."""
    mpath = tmp_path / "m.json"
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, f"-metrics-json={mpath}"]
    )
    assert rv == 0, err
    raw = mpath.read_text()
    assert raw.endswith("\n") and "\n" not in raw[:-1]  # single line
    payload = json.loads(raw)
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert payload["schema"] == golden["schema"] == SCHEMA
    assert sorted(payload) == sorted(golden["top_level_keys"])
    for sp in payload["spans"]:
        base = set(golden["span_keys"])
        assert base <= set(sp) <= base | {"attrs"}
    for ev in payload["events"]:
        assert set(golden["event_base_keys"]) <= set(ev)
    names = {s["name"] for s in payload["spans"]}
    assert {"validate_flags", "parse_input", "plan", "emit"} <= names
    assert payload["rc"] == 0
    assert payload["counters"]["cli.changes_written"] >= 1


def test_metrics_json_dash_is_last_stdout_line():
    rv, out, _err = run_cli(
        ["-input-json", "-input", FIXTURE, "-metrics-json=-"]
    )
    assert rv == 0
    lines = out.strip().splitlines()
    assert lines[0].startswith('{"version"')  # the plan comes first
    payload = json.loads(lines[-1])
    assert payload["schema"] == SCHEMA


def test_trace_file_is_valid_chrome_trace(tmp_path):
    tpath = tmp_path / "t.json"
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, f"-trace={tpath}"]
    )
    assert rv == 0, err
    with open(tpath) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    xs = [ev for ev in evs if ev["ph"] == "X"]
    assert xs
    for ev in xs:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(ev)
        assert ev["ts"] >= 0 and ev["dur"] >= 0
        assert ev["pid"] == os.getpid()
    ts = [ev["ts"] for ev in xs]
    assert ts == sorted(ts)  # recorded under one lock: start-ordered
    # every tid carries a thread_name metadata track
    tids = {ev["tid"] for ev in xs}
    named = {
        ev["tid"]
        for ev in evs
        if ev["ph"] == "M" and ev["name"] == "thread_name"
    }
    assert tids <= named


def test_stats_summary_goes_to_stderr():
    rv, _out, err = run_cli(["-input-json", "-input", FIXTURE, "-stats"])
    assert rv == 0
    assert "invocation telemetry" in err
    assert "parse_input" in err and "emit" in err
    assert "rc=0" in err


def test_disabled_trio_writes_no_files(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rv, _out, _err = run_cli(["-input-json", "-input", FIXTURE])
    assert rv == 0
    assert os.listdir(tmp_path) == []


def test_exit3_error_path_still_exports(tmp_path):
    mpath = tmp_path / "m.json"
    rv, _out, _err = run_cli(
        ["-input-json", "-max-reassign=-1", f"-metrics-json={mpath}"]
    )
    assert rv == 3
    payload = json.loads(mpath.read_text())
    assert payload["rc"] == 3 and payload["schema"] == SCHEMA
    # the lifecycle got as far as flag validation — and said so
    assert "validate_flags" in {s["name"] for s in payload["spans"]}


def test_exit4_error_path_still_exports(tmp_path):
    class Boom(io.StringIO):
        def write(self, s):
            raise OSError("sink failed")

    from kafkabalancer_tpu.cli import run

    mpath = tmp_path / "m.json"
    with open(FIXTURE) as f:
        src = f.read()
    rv = run(
        io.StringIO(src), Boom(), io.StringIO(),
        ["kafkabalancer", "-input-json", f"-metrics-json={mpath}"],
    )
    assert rv == 4
    payload = json.loads(mpath.read_text())
    assert payload["rc"] == 4
    assert "emit" in {s["name"] for s in payload["spans"]}


def test_flag_error_exit_with_trio_never_imports_jax(tmp_path):
    """The cold-path guarantee (tests/test_coldstart.py) must survive
    the full telemetry trio: obs/ is jax-free, so an argument-error exit
    with -stats -metrics-json -trace all enabled still exits 3 without
    touching jax — and still exports."""
    mpath = str(tmp_path / "m.json")
    tpath = str(tmp_path / "t.json")
    code = (
        "import io, sys\n"
        "from kafkabalancer_tpu.cli import run\n"
        "rc = run(io.StringIO(''), io.StringIO(), io.StringIO(),\n"
        "         ['kafkabalancer', '-input-json', '-solver=tpu',\n"
        f"          '-max-reassign=-1', '-stats', '-metrics-json={mpath}',\n"
        f"          '-trace={tpath}'])\n"
        "assert rc == 3, rc\n"
        "bad = [m for m in sys.modules if m == 'jax' or m.startswith('jax.')]\n"
        "assert not bad, f'jax imported on an error exit: {bad[:3]}'\n"
        "assert 'kafkabalancer_tpu.solvers.scan' not in sys.modules\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert json.loads(open(mpath).read())["rc"] == 3
    assert json.load(open(tpath))["traceEvents"]


def test_fused_lifecycle_spans_cover_background_warmup(tmp_path, monkeypatch):
    """Acceptance pin: a -fused run's metrics JSON carries the lifecycle
    — parse, the warmup on its own BACKGROUND thread (parented to the
    launch site), the session dispatch, and emit."""
    monkeypatch.setenv("KAFKABALANCER_TPU_NO_AOT", "1")
    mpath = tmp_path / "m.json"
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, "-fused", "-fused-batch=4",
         "-max-reassign=4", f"-metrics-json={mpath}"]
    )
    assert rv == 0, err
    payload = json.loads(mpath.read_text())
    spans = payload["spans"]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    names = set(by_name)
    assert {
        "parse_input", "warm_thread_launch", "plan",
        "solver.dispatch_chunk", "tensorize", "emit",
    } <= names, sorted(names)
    launch = by_name["warm_thread_launch"][0]
    warm = by_name["coldstart.warm"][0]
    assert warm["thread"] != launch["thread"]  # its own thread track...
    assert warm["parent"] == launch["sid"]  # ...linked to the launch site
    # the fused dispatch is nested under the plan span
    plan_sids = {s["sid"] for s in by_name["plan"]}
    assert by_name["solver.dispatch_chunk"][0]["parent"] in plan_sids
    # and the session counters made it into the registry
    assert payload["counters"]["solver.chunks"] >= 1
    assert payload["counters"]["solver.moves_committed"] >= 1


# --- -pprof-path satellite ------------------------------------------------


def test_pprof_path_flag_redirects_profile(tmp_path):
    p = tmp_path / "prof.pb.gz"
    rv, _out, _err = run_cli(
        ["-input-json", "-input", FIXTURE, "-pprof", f"-pprof-path={p}"]
    )
    assert rv == 0
    assert gzip.open(p, "rb").read()  # gzipped profile.proto, non-empty


def test_pprof_default_path_unchanged(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    rv, _out, _err = run_cli(["-input-json", "-input", FIXTURE, "-pprof"])
    assert rv == 0
    assert (tmp_path / "cpu.pprof").exists()


def test_pprof_write_failure_logged_not_fatal(tmp_path):
    bad = tmp_path / "no-such-dir" / "cpu.pprof"
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, "-pprof", f"-pprof-path={bad}"]
    )
    assert rv == 0  # the plan must not fail on a profile-write failure
    assert "failed writing cpu profile" in err


def test_shared_registry_mode_keeps_stores_and_refcounts_tracing():
    """Multi-lane serving mode: begin_invocation keeps the
    daemon-lifetime registry (no reset), and the tracer drops back to
    the no-op fast path when the LAST tracing request finishes."""
    from kafkabalancer_tpu import obs

    obs.begin_invocation()  # clean slate (unshared reset)
    obs.set_shared_registry(True)
    try:
        obs.metrics.count("x.requests")
        obs.begin_invocation()  # shared: must NOT reset
        assert obs.REGISTRY.counter_get("x.requests") == 1.0

        assert not obs.tracer.enabled
        obs.enable_tracing()  # request A (-stats)
        obs.enable_tracing()  # request B (-metrics-json), concurrent
        assert obs.tracer.enabled
        obs.end_invocation()  # A finishes: B still tracing
        assert obs.tracer.enabled
        obs.end_invocation()  # B finishes: back to the no-op fast path
        assert not obs.tracer.enabled
        # recorded spans survive the disable (trim owns the bound)
        obs.end_invocation()  # over-release is harmless
        assert not obs.tracer.enabled
    finally:
        obs.set_shared_registry(False)
        obs.begin_invocation()


def test_tracer_trim_keeps_inflight_and_newest_spans():
    from kafkabalancer_tpu.obs.trace import Tracer

    tr = Tracer()
    tr.enable()
    open_span = tr.span("inflight")
    open_span.__enter__()
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    tr.trim(cap=3)
    names = [s["name"] for s in tr.snapshot()]
    assert "inflight" in names  # in-flight spans are never dropped
    assert len(names) == 3
    assert names[-1] == "s9"  # oldest completed dropped first
    open_span.__exit__(None, None, None)


# --- streaming histograms (obs/hist.py) -----------------------------------


def test_hist_bucket_boundaries():
    """Bucket math pin: every value lands in the smallest bucket whose
    upper bound holds it, at SUBBUCKETS buckets per octave; values <= 0
    go to the underflow bucket (upper bound 0.0)."""
    from kafkabalancer_tpu.obs import hist as obs_hist

    for v in (1e-6, 0.0013, 0.5, 1.0, 3.0, 1000.0, 7e6):
        i = obs_hist.bucket_index(v)
        assert obs_hist.bucket_le(i) >= v, v
        assert obs_hist.bucket_le(i - 1) < v, v
    assert obs_hist.bucket_index(1.0) == 0  # 2**0 is a bucket boundary
    assert obs_hist.bucket_le(0) == 1.0
    for v in (0.0, -1.0, float("nan")):
        assert obs_hist.bucket_index(v) == obs_hist.UNDERFLOW
    assert obs_hist.bucket_le(obs_hist.UNDERFLOW) == 0.0


def test_hist_percentiles_within_one_bucket():
    """p50/p95/p99 of a known distribution come back as the true value's
    bucket upper bound — conservative within one bucket's ~19% width."""
    from kafkabalancer_tpu.obs.hist import StreamingHist, bucket_index, bucket_le

    h = StreamingHist()
    for ms in range(1, 101):
        h.observe(ms / 1000.0)
    s = h.snapshot()
    assert s["count"] == 100
    assert s["min"] == 0.001 and s["max"] == 0.1
    assert abs(s["sum"] - sum(ms / 1000.0 for ms in range(1, 101))) < 1e-6
    for q, true in (("p50", 0.050), ("p95", 0.095), ("p99", 0.099)):
        le = bucket_le(bucket_index(true))
        assert true <= s[q] <= le * 1.20, (q, s[q])
    assert s["buckets"] and all(n >= 1 for _le, n in s["buckets"])
    assert [le for le, _n in s["buckets"]] == sorted(
        le for le, _n in s["buckets"]
    )


def test_hist_merge_buckets_matches_combined_stream():
    from kafkabalancer_tpu.obs.hist import (
        StreamingHist,
        merge_buckets,
        percentile_from_buckets,
    )

    a, b, both = StreamingHist(), StreamingHist(), StreamingHist()
    for v in (0.001, 0.002, 0.004):
        a.observe(v)
        both.observe(v)
    for v in (0.1, 0.2, 0.4, 0.8):
        b.observe(v)
        both.observe(v)
    merged = merge_buckets([a._buckets, b._buckets])
    assert sum(merged.values()) == 7
    for q in (0.5, 0.95, 0.99):
        assert percentile_from_buckets(merged, q) == both.percentile(q)


def test_hist_merge_from_opposite_directions_no_deadlock():
    """Regression for the R7 contract-lint finding: two hists merged in
    opposite directions on two threads take the SAME lock pair in
    opposite orders — merge_from id-orders the acquisition, so the
    classic unordered-pair deadlock cannot fire. Also pins the
    self-merge no-op (the same non-reentrant lock twice)."""
    from kafkabalancer_tpu.obs.hist import StreamingHist

    a, b = StreamingHist(), StreamingHist()
    a.observe(1.0)
    b.observe(2.0)
    a.merge_from(a)  # self-merge: no-op, must not self-deadlock
    assert a.snapshot()["count"] == 1

    start = threading.Barrier(2)

    def fold(dst, src):
        start.wait()
        for _ in range(300):
            dst.merge_from(src)

    threads = [
        threading.Thread(target=fold, args=(a, b)),
        threading.Thread(target=fold, args=(b, a)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "merge_from deadlocked"


def test_hist_windowed_rotation():
    """The ring of sub-epoch buckets: observations age out of the
    windowed view after window_s while the lifetime view keeps them."""
    from kafkabalancer_tpu.obs.hist import StreamingHist

    clock = [0.0]
    h = StreamingHist(window_s=60.0, ring=6, now=lambda: clock[0])
    h.observe(1.0)
    clock[0] = 30.0
    h.observe(2.0)
    s = h.snapshot()
    assert s["count"] == 2 and s["window"]["count"] == 2
    clock[0] = 70.0  # the t=0 slot aged out; t=30 still inside
    s = h.snapshot()
    assert s["count"] == 2 and s["window"]["count"] == 1
    clock[0] = 500.0  # everything aged out; lifetime survives
    s = h.snapshot()
    assert s["count"] == 2 and s["window"]["count"] == 0
    assert s["window"]["span_s"] == 60.0


def test_registry_hist_family_is_process_lifetime():
    """Registry integration: hist_observe feeds a named streaming hist;
    reset() (the per-invocation epoch) leaves histograms alone — they
    are daemon-lifetime by design — and snapshot() excludes them (the
    metrics/1 golden schema must not move); reset_hists clears."""
    from kafkabalancer_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.hist_observe("x.latency", 0.5)
    reg.hist_observe("x.latency", 1.5)
    reg.count("n")
    assert "histograms" not in reg.snapshot()
    assert "hists" not in reg.snapshot()
    snap = reg.hist_snapshot()
    assert snap["x.latency"]["count"] == 2
    reg.reset()
    assert reg.counter_get("n") == 0.0
    assert reg.hist_snapshot()["x.latency"]["count"] == 2  # survived
    reg.reset_hists()
    assert reg.hist_snapshot() == {}


def test_registry_hist_concurrent_observers():
    from kafkabalancer_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()

    def body(k):
        for i in range(500):
            reg.hist_observe("shared", float(i % 7 + 1))
            reg.hist_observe(f"own{k}", 1.0)

    threads = [threading.Thread(target=body, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.hist_snapshot()
    assert snap["shared"]["count"] == 4000
    assert all(snap[f"own{k}"]["count"] == 500 for k in range(8))


# --- tracer observer seam (the daemon's always-on feed) -------------------


def test_tracer_observer_times_spans_without_recording():
    """With an observer installed and recording DISABLED, span sites
    time real spans and hand them to the observer at exit — innermost
    first — while the recorded span list stays empty; removing the
    observer restores the shared no-op singleton."""
    from kafkabalancer_tpu.obs.trace import Tracer

    tr = Tracer()
    seen = []
    tr.set_observer(lambda sp: seen.append((sp.name, sp.t1_ns)))
    with tr.span("outer"):
        with tr.span("inner"):
            pass
    assert [n for n, _t1 in seen] == ["inner", "outer"]
    assert all(t1 is not None for _n, t1 in seen)
    assert tr.snapshot() == []  # observer-only spans are never recorded
    tr.set_observer(None)
    assert tr.span("after") is NOOP_SPAN


def test_tracer_observer_exceptions_never_break_span_sites():
    from kafkabalancer_tpu.obs.trace import Tracer

    tr = Tracer()
    tr.set_observer(lambda sp: (_ for _ in ()).throw(RuntimeError("boom")))
    try:
        with tr.span("guarded"):
            pass  # must not raise
    finally:
        tr.set_observer(None)


def test_observer_only_span_never_becomes_recorded_parent():
    """Mid-flight enable: a span recorded while an observer-only span
    (sid 0) is still open on the thread stack exports as a ROOT, not
    with a dangling parent_sid=0."""
    from kafkabalancer_tpu.obs.trace import Tracer

    tr = Tracer()
    tr.set_observer(lambda sp: None)
    try:
        with tr.span("observer-only"):
            tr.enable()  # a concurrent -trace request switched it on
            with tr.span("recorded"):
                pass
    finally:
        tr.set_observer(None)
        tr.disable()
    spans = {s["name"]: s for s in tr.snapshot()}
    assert set(spans) == {"recorded"}
    assert spans["recorded"]["parent"] is None


def test_tracer_observer_also_sees_enabled_spans():
    from kafkabalancer_tpu.obs.trace import Tracer

    tr = Tracer()
    tr.enable()
    seen = []
    tr.set_observer(lambda sp: seen.append(sp.name))
    try:
        with tr.span("both"):
            pass
    finally:
        tr.set_observer(None)
    assert seen == ["both"]
    assert [s["name"] for s in tr.snapshot()] == ["both"]


# --- flight recorder (obs/flight.py) --------------------------------------


def test_flight_span_ring_wraparound():
    from kafkabalancer_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(span_cap=8, request_cap=4)
    for i in range(20):
        fr.note_span(f"s{i}", i * 1000, i * 1000 + 500, "worker", 7, None)
    assert fr.stats()["spans"] == 8
    doc = fr.to_perfetto()
    xs = [ev for ev in doc["traceEvents"] if ev["ph"] == "X"]
    names = [ev["name"] for ev in xs]
    assert len(names) == 8 and names[-1] == "s19" and "s0" not in names
    for ev in xs:
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(ev)
        assert ev["ts"] >= 0 and ev["dur"] >= 0
    # thread_name metadata track present for the span tid
    assert any(
        ev["ph"] == "M" and ev["name"] == "thread_name" and ev["tid"] == 7
        for ev in doc["traceEvents"]
    )


def test_flight_request_ring_wraparound():
    from kafkabalancer_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder(span_cap=8, request_cap=4)
    for i in range(9):
        fr.record_request({"req": i})
    assert [r["req"] for r in fr.request_log()] == [5, 6, 7, 8]
    assert fr.to_perfetto()["otherData"]["requests"][-1]["req"] == 8


def test_flight_phase_accumulation_by_request_thread():
    """Spans on a serve-req-N thread accumulate into that request's
    phase map (dispatch rounds SUM); other threads accumulate nothing;
    pop clears."""
    from kafkabalancer_tpu.obs.flight import FlightRecorder

    fr = FlightRecorder()
    fr.note_span("parse_input", 0, 2_000_000, "serve-req-3", 1, None)
    fr.note_span("solver.dispatch_chunk", 0, 1_000_000, "serve-req-3", 1, None)
    fr.note_span("solver.dispatch_chunk", 0, 3_000_000, "serve-req-3", 1, None)
    fr.note_span("parse_input", 0, 9_000_000, "MainThread", 2, None)
    fr.note_span("unmapped_span", 0, 9_000_000, "serve-req-3", 1, None)
    phases = fr.pop_request_phases("serve-req-3")
    assert abs(phases["parse"] - 0.002) < 1e-9
    assert abs(phases["dispatch"] - 0.004) < 1e-9
    assert set(phases) == {"parse", "dispatch"}
    assert fr.pop_request_phases("serve-req-3") == {}  # popped
    assert fr.pop_request_phases("MainThread") == {}


def test_flight_autodump_writes_perfetto_and_caps(tmp_path):
    from kafkabalancer_tpu.obs import flight as obs_flight

    fr = obs_flight.FlightRecorder(span_cap=16, request_cap=4)
    fr.note_span("tensorize", 0, 5_000_000, "serve-req-1", 3, {"k": 1})
    fr.record_request({"req": 1, "rc": 0, "wall_s": 0.005})
    logs = []
    path = fr.autodump("slow-req-1", directory=str(tmp_path), log=logs.append)
    assert path and os.path.exists(path)
    assert "slow-req-1" in path
    with open(path) as f:
        doc = json.load(f)
    assert [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert doc["otherData"]["requests"][0]["req"] == 1
    assert any("dumped" in m for m in logs)
    # storm rate limit: a second dump inside the min interval is
    # SUPPRESSED (counted, not written) — a shed/crash storm must not
    # burn the whole dump budget in its first second
    assert fr.autodump("storm", directory=str(tmp_path)) is None
    assert fr.stats()["autodumps_suppressed"] == 1
    # the per-process cap: past MAX_AUTODUMPS, dumps are refused
    # (min_interval_s=0 disables the rate limit to exercise the cap)
    for i in range(obs_flight.MAX_AUTODUMPS):
        fr.autodump(f"r{i}", directory=str(tmp_path), min_interval_s=0)
    assert (
        fr.autodump("over", directory=str(tmp_path), min_interval_s=0)
        is None
    )
    assert fr.stats()["autodumps"] == obs_flight.MAX_AUTODUMPS


# --- Prometheus exposition: histogram _count/_sum + memory gauges ----------


def test_prometheus_summaries_carry_count_and_sum():
    """The satellite pin: every histogram summary must emit BOTH
    ``_count`` and ``_sum`` lines (without them ``rate()`` over phase
    totals is impossible in standard scrapers)."""
    doc = {
        "requests": 3,
        "uptime_s": 1.5,
        "hists": {
            "serve.phase.parse": {
                "count": 3, "sum": 0.123456, "p50": 0.01, "p95": 0.02,
                "p99": 0.03,
            },
            "serve.request_s": {
                "count": 3, "sum": 1.5, "p50": 0.4, "p95": 0.6, "p99": 0.7,
            },
        },
    }
    text = obs_export.render_prometheus(doc)
    for name in ("serve_phase_parse", "serve_request_s"):
        m = f"kafkabalancer_tpu_{name}"
        assert f"# TYPE {m} summary" in text
        assert f"{m}_count 3" in text, text
        assert f"{m}_sum " in text, text
        for q in ("0.5", "0.95", "0.99"):
            assert f'{m}{{quantile="{q}"}}' in text


def test_prometheus_memory_gauges_labeled_per_lane():
    doc = {
        "requests": 1,
        "memory": [
            {"lane": 0, "hbm_bytes_in_use": 1024, "hbm_bytes_limit": 4096,
             "residency_bytes": 512, "residency_entries": 2},
            {"lane": 1, "hbm_bytes_in_use": None, "hbm_bytes_limit": None,
             "residency_bytes": 0, "residency_entries": 0},
        ],
        "hists": {},
    }
    text = obs_export.render_prometheus(doc)
    assert '# TYPE kafkabalancer_tpu_lane_hbm_bytes_in_use gauge' in text
    assert 'kafkabalancer_tpu_lane_hbm_bytes_in_use{lane="0"} 1024' in text
    # null stats (backend without introspection) are omitted, not 0
    assert 'lane_hbm_bytes_in_use{lane="1"}' not in text
    assert 'kafkabalancer_tpu_lane_residency_bytes{lane="1"} 0' in text


def test_serve_stats_human_rendering_shows_memory():
    doc = {
        "pid": 1, "version": "x", "uptime_s": 2.0, "requests": 1,
        "coalesced": 0, "requests_inflight": 0, "slow_requests": 0,
        "crashed_requests": 0, "batch_mode": "continuous",
        "memory": [
            {"lane": 0, "hbm_bytes_in_use": 2_500_000,
             "hbm_bytes_limit": None, "residency_bytes": 1_000_000,
             "residency_entries": 3},
        ],
        "hists": {},
    }
    text = obs_export.render_serve_stats(doc)
    assert "memory lane0: hbm 2.5MB, residency 1.0MB (3 entries)" in text


def test_render_stats_includes_streaming_hists():
    reg = MetricsRegistry()
    reg.hist_observe("aot.compile_s", 0.25)
    reg.hist_observe("aot.compile_s", 0.5)
    text = obs_export.render_stats(reg, Tracer())
    assert "hist aot.compile_s: n=2" in text


def test_aot_jit_path_observes_compile_hists(tmp_path, monkeypatch):
    """The device-memory/compile attribution tentpole: the AOT dispatch
    policy feeds streaming histograms (aot.jit_s on the jit path;
    aot.compile_s on the AOT lower+compile; aot.deserialize_s on blob
    loads) that ride the stats scrape and -metrics-prom."""
    jax = pytest.importorskip("jax")
    import numpy as np

    from kafkabalancer_tpu.ops import aot

    monkeypatch.setenv("KAFKABALANCER_TPU_AOT_SYNC_SAVE", "1")
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path))
    obs.metrics.reset_hists()
    fn = jax.jit(lambda x: x + 1)
    out = aot.call_or_compile("hist_probe", fn, (np.arange(4),), {})
    assert np.asarray(out).tolist() == [1, 2, 3, 4]
    snap = obs.metrics.hist_snapshot()
    assert "aot.jit_s" in snap and snap["aot.jit_s"]["count"] >= 1
    obs.metrics.reset_hists()
