"""Tensorization round-trip and JAX cost-model parity tests.

The float64 host oracle (kafkabalancer_tpu.balancer.costmodel, itself pinned
against the Go reference by the golden tests) is the ground truth; the JAX
cost model must agree to float64 round-off."""

import math
import random

import numpy as np
import pytest

from helpers import random_partition_list

from kafkabalancer_tpu.balancer import steps as _s
from kafkabalancer_tpu.balancer.costmodel import (
    get_bl,
    get_broker_load,
    get_unbalance_bl,
)
from kafkabalancer_tpu.models import default_rebalance_config
from kafkabalancer_tpu.ops import cost, tensorize
from kafkabalancer_tpu.ops.runtime import ensure_x64, next_bucket

ensure_x64()

import jax.numpy as jnp  # noqa: E402


def filled(pl, cfg=None):
    cfg = cfg or default_rebalance_config()
    _s.fill_defaults(pl, cfg)
    return pl


def test_next_bucket():
    assert next_bucket(0) == 8
    assert next_bucket(8) == 8
    assert next_bucket(9) == 16
    assert next_bucket(1000) == 1024


def test_scale_bucket_fine_ladder():
    """The SCALE tier's fine partition-bucket ladder (multiples of
    8 × part-axis size above ~64k rows): padded-row counts are pinned —
    the power-of-two ladder pads a 100k-row cluster with 31,072 dead
    rows, the fine ladder with 32."""
    from kafkabalancer_tpu.ops.runtime import scale_bucket

    # below the threshold: exactly the power-of-two ladder on the step
    assert scale_bucket(1000, 64) == 1024
    assert scale_bucket(65536, 64) == 65536
    assert scale_bucket(0, 64) == 64
    # above: multiples of the step — padding bounded by step - 1
    assert scale_bucket(100_000, 64) == 100_032   # pow2: 131072
    assert scale_bucket(100_032, 64) == 100_032   # exact multiples stick
    assert scale_bucket(1_000_000, 64) == 1_000_000
    assert scale_bucket(1_000_001, 64) == 1_000_064
    # padded-row pins: fine vs doubling
    assert scale_bucket(100_000, 64) - 100_000 == 32
    assert next_bucket(100_000, 64) - 100_000 == 31_072
    # odd part-axis sizes keep divisibility (S=6 -> step 48)
    assert scale_bucket(100_000, 48) % 48 == 0
    assert scale_bucket(100_000, 48) - 100_000 < 48
    # every bucket divides by the step (the P % S contract)
    for n in (5, 70_000, 131_073):
        assert scale_bucket(n, 64) % 64 == 0
        assert scale_bucket(n, 64) >= n


def test_tensorize_lean_scale_encode():
    """The lean sharded-encode seam: p_bucket overrides the row bucket
    (fine ladder) and build_member=False skips the [P, B] membership
    table — everything else identical to the full encode."""
    import numpy as np

    from kafkabalancer_tpu.models import default_rebalance_config
    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl = synth_cluster(100, 8, rf=2, seed=3, weighted=True)
    cfg = default_rebalance_config()
    full = tensorize(pl, cfg, min_bucket=16)
    lean = tensorize(pl, cfg, min_bucket=16, p_bucket=112,
                     build_member=False)
    assert lean.member is None
    assert lean.replicas.shape[0] == 112
    n = lean.np_
    assert n == full.np_
    np.testing.assert_array_equal(lean.replicas[:n], full.replicas[:n])
    np.testing.assert_array_equal(lean.allowed[:n], full.allowed[:n])
    np.testing.assert_array_equal(lean.weights[:n], full.weights[:n])
    assert not lean.pvalid[n:].any()
    with pytest.raises(ValueError, match="p_bucket"):
        tensorize(pl, cfg, p_bucket=50)


def test_tensorize_round_trip():
    rng = random.Random(7)
    for trial in range(8):
        pl = filled(
            random_partition_list(
                rng, rng.randint(1, 40), rng.randint(2, 12),
                weighted=bool(trial % 2), with_consumers=True,
                restrict_brokers=True, max_rf=4,
            )
        )
        dp = tensorize(pl)
        decoded = dp.decode_replicas(dp.replicas, dp.nrep_cur)
        for p, reps in zip(pl.partitions, decoded):
            assert reps == p.replicas
        # member/allowed masks agree with the ragged truth
        for i, p in enumerate(pl.partitions):
            for j, bid in enumerate(dp.broker_ids):
                assert dp.member[i, j] == (bid in p.replicas)
                assert dp.allowed[i, j] == (bid in p.brokers)
        # padding invariants
        assert not dp.pvalid[dp.np_ :].any()
        assert not dp.allowed[dp.np_ :].any()
        assert (dp.weights[dp.np_ :] == 0).all()
        assert not dp.bvalid[dp.nb :].any()


def test_tensorize_extra_brokers_extend_universe():
    rng = random.Random(3)
    pl = filled(random_partition_list(rng, 5, 4))
    base = tensorize(pl)
    ext = tensorize(pl, extra_brokers=[99999, 100000])
    assert ext.nb == base.nb + 2
    assert 99999 in ext.broker_ids


def test_broker_loads_matches_oracle():
    rng = random.Random(11)
    for _ in range(8):
        pl = filled(
            random_partition_list(
                rng, rng.randint(1, 60), rng.randint(2, 15),
                with_consumers=True, max_rf=5,
            )
        )
        dp = tensorize(pl)
        loads = np.asarray(
            cost.broker_loads(
                jnp.asarray(dp.replicas), jnp.asarray(dp.weights),
                jnp.asarray(dp.nrep_cur), jnp.asarray(dp.ncons),
                dp.bvalid.shape[0],
            )
        )
        oracle = get_broker_load(pl)
        for j, bid in enumerate(dp.broker_ids):
            assert loads[j] == pytest.approx(oracle.get(int(bid), 0.0), rel=1e-13)
        assert (loads[dp.nb :] == 0).all()


def test_unbalance_matches_oracle():
    rng = random.Random(13)
    for _ in range(8):
        pl = filled(
            random_partition_list(
                rng, rng.randint(1, 60), rng.randint(2, 15), with_consumers=True
            )
        )
        dp = tensorize(pl)
        loads = cost.broker_loads(
            jnp.asarray(dp.replicas), jnp.asarray(dp.weights),
            jnp.asarray(dp.nrep_cur), jnp.asarray(dp.ncons), dp.bvalid.shape[0],
        )
        u = float(cost.unbalance(loads, jnp.asarray(dp.bvalid), float(dp.nb)))
        oracle = get_unbalance_bl(get_bl(get_broker_load(pl)))
        assert u == pytest.approx(oracle, rel=1e-12, abs=1e-15)


def test_unbalance_nan_on_all_zero_loads():
    # all-zero loads: avg = 0, rel = 0/0 = NaN → NaN objective, like the Go
    # float64 path (utils.go:129-134 via IEEE division)
    u = float(cost.unbalance(jnp.zeros(4), jnp.ones(4, bool), 4.0))
    assert math.isnan(u)


def test_rank_brokers_matches_bl_order():
    rng = random.Random(17)
    for _ in range(8):
        pl = filled(random_partition_list(rng, 30, rng.randint(2, 12)))
        dp = tensorize(pl)
        loads_np = np.zeros(dp.bvalid.shape[0])
        oracle_loads = get_broker_load(pl)
        for j, bid in enumerate(dp.broker_ids):
            loads_np[j] = oracle_loads.get(int(bid), 0.0)
        loads_rank, perm, rank_of = cost.rank_brokers(
            jnp.asarray(loads_np), jnp.asarray(dp.bvalid)
        )
        bl = get_bl(oracle_loads)
        ranked_ids = [int(dp.broker_ids[int(perm[r])]) for r in range(dp.nb)]
        assert ranked_ids == [bid for bid, _ in bl]
        np.testing.assert_allclose(
            np.asarray(loads_rank)[: dp.nb], [load for _, load in bl], rtol=1e-13
        )
        # rank_of inverts perm
        perm_np = np.asarray(perm)
        assert (np.asarray(rank_of)[perm_np] == np.arange(len(perm_np))).all()
        # padded brokers rank last
        assert (perm_np[dp.nb :] >= dp.nb).all() or dp.nb == dp.bvalid.shape[0]


@pytest.mark.parametrize("allow_leader", [False, True])
def test_factored_target_best_top2_matches_exclude_call(allow_leader):
    """top2=True must return exactly what a second full call with
    exclude_p=<first winners> returns (the beam sibling-expansion
    contract) — one pass vs re-score is a pure efficiency change."""
    rng = random.Random(4242 + allow_leader)
    for _ in range(6):
        pl = filled(random_partition_list(
            rng, rng.randint(8, 40), rng.randint(3, 10),
            weighted=True, with_consumers=True,
        ))
        dp = tensorize(pl)
        loads = cost.broker_loads(
            jnp.asarray(dp.replicas),
            jnp.asarray(dp.weights),
            jnp.asarray(dp.nrep_cur),
            jnp.asarray(dp.ncons),
            dp.bvalid.shape[0],
        )
        args = (
            loads,
            jnp.asarray(dp.replicas),
            jnp.asarray(dp.allowed),
            jnp.asarray(dp.member),
            jnp.asarray(dp.bvalid),
            jnp.asarray(dp.weights),
            jnp.asarray(dp.nrep_cur),
            jnp.asarray(dp.nrep_tgt),
            jnp.asarray(dp.ncons),
            jnp.asarray(dp.pvalid),
            jnp.asarray(float(dp.nb)),
            2,
        )
        su, v1, p1, s1, v2, p2, s2 = cost.factored_target_best(
            *args, allow_leader=allow_leader, top2=True
        )
        su_a, v1_a, p1_a, s1_a = cost.factored_target_best(
            *args, allow_leader=allow_leader
        )
        su_b, v2_b, p2_b, s2_b = cost.factored_target_best(
            *args, allow_leader=allow_leader, exclude_p=p1_a
        )
        assert float(su) == float(su_a) == float(su_b)
        for got, want in ((v1, v1_a), (v2, v2_b)):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        for got, want in ((p1, p1_a), (s1, s1_a), (p2, p2_b), (s2, s2_b)):
            assert (np.asarray(got) == np.asarray(want)).all()


def test_persistent_cache_default(tmp_path):
    """Fresh processes point JAX at the XDG persistent compile cache by
    default (the deployment model is one stateless process per move, so
    without it every CLI invocation pays full compiles); env opt-out and
    a pre-set JAX_COMPILATION_CACHE_DIR win."""
    import os as _os
    import subprocess
    import sys

    code = (
        "import jax\n"
        "from kafkabalancer_tpu.ops.runtime import ensure_x64\n"
        "ensure_x64()\n"
        "print(repr(jax.config.jax_compilation_cache_dir))\n"
    )

    def run(extra_env):
        env = dict(_os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XDG_CACHE_HOME"] = str(tmp_path)
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        env.pop("KAFKABALANCER_TPU_NO_COMPILE_CACHE", None)
        env.pop("KAFKABALANCER_TPU_COMPILE_CACHE", None)
        env.update(extra_env)
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120, env=env,
            cwd=_os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        )
        assert out.returncode == 0, out.stderr[-1000:]
        return out.stdout.strip().splitlines()[-1]

    # CPU-pinned processes (tests/CI/dryrun) skip the default — CPU
    # executables are machine-feature-sensitive in shared caches
    assert run({}) == "None"
    got = run({"KAFKABALANCER_TPU_COMPILE_CACHE": "1"})
    assert str(tmp_path) in got and "jax-cache" in got
    assert _os.path.isdir(
        _os.path.join(str(tmp_path), "kafkabalancer-tpu", "jax-cache")
    )
    assert run({
        "KAFKABALANCER_TPU_COMPILE_CACHE": "1",
        "KAFKABALANCER_TPU_NO_COMPILE_CACHE": "1",
    }) == "None"
    assert "/elsewhere" in run({"JAX_COMPILATION_CACHE_DIR": "/elsewhere"})
    # composite priority lists whose FIRST entry is cpu are just as
    # CPU-pinned as the exact value "cpu"
    assert run({"JAX_PLATFORMS": "cpu,tpu"}) == "None"
    assert run({"JAX_PLATFORMS": " CPU , tpu "}) == "None"
