"""Overload protection + fault tolerance (serve/admission.py,
serve/faults.py, the lane health monitor, the client backoff/wedge
ladder, pidfile-verified stale-socket takeover).

The load-bearing pins:

- the fault seam is INERT by default — an unarmed process carries no
  schedule and ``fire`` is one None check;
- shedding answers a structured ``{op: "overload", retry_after_ms}``
  frame instead of queueing forever, lands in ``serve.shed_s`` (never
  ``serve.request_s``), and the DRR grant order starves no tenant;
- deadlines shed QUEUED requests only — never in-flight ones;
- a crashed or wedged lane is quarantined: its in-flight work answers a
  structured error (never a wrong plan), its queued work requeues onto
  healthy lanes and still plans byte-identically, and the lane
  recovers;
- the client honors ``retry_after_ms`` with capped jittered backoff
  before its byte-identical in-process fallback, and detects a wedged
  daemon in seconds (``serve.fallbacks.daemon_wedged``) instead of
  hanging for an hour;
- a SIGKILL'd daemon's leftovers are swept on restart, but a live
  process's socket is never hijacked.
"""

import io
import json
import os
import shutil
import socket as socket_mod
import struct
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from kafkabalancer_tpu import __version__, cli, obs
from kafkabalancer_tpu.serve import client as sclient
from kafkabalancer_tpu.serve import faults, protocol
from kafkabalancer_tpu.serve.admission import AdmissionController
from kafkabalancer_tpu.serve.daemon import Coalescer, Daemon, PlanRequest

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")


def run_cli(args, stdin=""):
    out, err = io.StringIO(), io.StringIO()
    rv = cli.run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


@pytest.fixture
def sock_dir():
    d = tempfile.mkdtemp(prefix="kbo-")
    yield d
    shutil.rmtree(d, ignore_errors=True)


def _start_daemon(sock, **kw):
    kw.setdefault("idle_timeout", 60.0)
    kw.setdefault("warm", False)
    kw.setdefault("log", lambda _m: None)
    d = Daemon(sock, **kw)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            return d, t, rc_box
        time.sleep(0.02)
    pytest.fail("daemon never became ready")


class _Req:
    """Minimal admission-facing request."""

    def __init__(self, tenant="", deadline=None):
        self.tenant = tenant
        self.deadline = deadline


# --- the fault seam -------------------------------------------------------


def test_fault_seam_inert_by_default():
    """The hot-path pin: no schedule unless armed, fire/should are
    no-ops, and disarm restores inertness."""
    assert faults.active() is None
    faults.fire("lane_crash")  # must not raise
    assert faults.should("socket_drop") is False
    plan = faults.arm("dispatch_delay@1:0.0;socket_drop@2")
    try:
        assert faults.active() is plan
        faults.fire("dispatch_delay")  # occurrence 1: scheduled, 0s sleep
        assert not faults.should("socket_drop")  # occurrence 1: not in plan
        assert faults.should("socket_drop")  # occurrence 2: fires
        assert plan.fired_counts() == {
            "dispatch_delay": 1, "socket_drop": 1,
        }
    finally:
        faults.disarm()
    assert faults.active() is None
    faults.fire("dispatch_delay")  # inert again


def test_fault_spec_parse_errors():
    for bad in ("nonsense", "unknown_site@1", "lane_crash@0",
                "lane_crash@x", "lane_crash"):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)
    plan = faults.parse_spec("lane_crash@3;transfer_fail@1,5:0.2")
    assert plan.spec.startswith("lane_crash@3")


def test_fault_fire_raises_scheduled():
    faults.arm("lane_crash@1;transfer_fail@1")
    try:
        with pytest.raises(BaseException) as ei:
            faults.fire("lane_crash")
        assert isinstance(ei.value, faults.LaneCrash)
        assert not isinstance(ei.value, Exception)  # escapes except nets
        with pytest.raises(faults.FaultError):
            faults.fire("transfer_fail")
    finally:
        faults.disarm()


# --- admission control ----------------------------------------------------


def test_admission_caps_shed_with_structured_frame():
    a = AdmissionController(window=1, max_queue=1, tenant_inflight=2)
    r1 = _Req("a")
    assert a.acquire(r1) is None  # granted
    # r2 queues; r3 overflows the total queue cap
    done = []
    t = threading.Thread(target=lambda: done.append(a.acquire(_Req("b"))))
    t.start()
    time.sleep(0.05)
    shed = a.acquire(_Req("c"))
    assert shed["ok"] is False and shed["op"] == "overload"
    assert shed["reason"] == "overload"
    assert shed["retry_after_ms"] >= 1
    # the per-tenant cap: tenant "a" holds 1 granted; with cap 2 a
    # second queues, a third sheds with reason "tenant". Lift the
    # total-queue cap FIRST so only the tenant cap binds.
    a.max_queue = 10
    t2 = threading.Thread(target=lambda: a.acquire(_Req("a")))
    t2.start()
    time.sleep(0.05)
    shed2 = a.acquire(_Req("a"))
    assert shed2["op"] == "overload" and shed2["reason"] == "tenant"
    a.stop()
    t.join(5)
    t2.join(5)


def test_admission_drr_fairness_no_starvation():
    """A whale tenant floods the queue; grants still alternate so the
    minnow is never starved behind the whale's backlog."""
    a = AdmissionController(window=1, max_queue=0, tenant_inflight=0)
    blocker = _Req("whale")
    assert a.acquire(blocker) is None
    order = []
    lock = threading.Lock()

    def waiter(tenant):
        r = _Req(tenant)
        if a.acquire(r) is None:
            with lock:
                order.append(tenant)
            a.release(r)

    threads = []
    # the whale enqueues a deep backlog first, then the minnow arrives
    for i in range(6):
        t = threading.Thread(target=waiter, args=("whale",))
        t.start()
        threads.append(t)
        time.sleep(0.02)
    for i in range(2):
        t = threading.Thread(target=waiter, args=("minnow",))
        t.start()
        threads.append(t)
        time.sleep(0.02)
    a.release(blocker)  # grants begin; each release grants the next
    for t in threads:
        t.join(10)
    assert sorted(order.count(x) for x in ("whale", "minnow")) == [2, 6]
    # DRR: the minnow's first grant must come long before the whale's
    # backlog drains (round-robin across tenants, not FIFO)
    assert "minnow" in order[:3], order


def test_admission_deadline_sheds_queued_never_inflight():
    now = [0.0]
    a = AdmissionController(
        window=1, max_queue=0, tenant_inflight=0, clock=lambda: now[0]
    )
    inflight = _Req("t", deadline=1.0)
    assert a.acquire(inflight) is None  # granted at t=0
    got = []
    queued = _Req("t", deadline=5.0)
    t = threading.Thread(target=lambda: got.append(a.acquire(queued)))
    t.start()
    time.sleep(0.05)
    # past BOTH deadlines: the queued request sheds on sweep, the
    # granted one is untouched (never shed in flight)
    now[0] = 10.0
    assert a.sweep() == 1
    t.join(5)
    assert got[0]["op"] == "overload" and got[0]["reason"] == "deadline"
    assert got[0]["retry_after_ms"] == 0
    st = a.stats()
    assert st["granted"] == 1 and st["sheds"] == {"deadline": 1}
    # arrival past its own deadline sheds immediately
    dead = a.acquire(_Req("t", deadline=3.0))
    assert dead["reason"] == "deadline"
    a.release(inflight)
    a.stop()


def test_sheds_land_in_shed_hist_not_request_hist():
    obs.metrics.reset_hists()
    a = AdmissionController(window=1, max_queue=1, tenant_inflight=0)
    r = _Req("t")
    assert a.acquire(r) is None
    t = threading.Thread(target=lambda: a.acquire(_Req("t")))
    t.start()
    time.sleep(0.05)
    assert a.acquire(_Req("t"))["op"] == "overload"
    hists = obs.metrics.hist_snapshot()
    assert hists["serve.shed_s"]["count"] == 1
    assert "serve.request_s" not in hists
    a.stop()
    t.join(5)


# --- lane health ----------------------------------------------------------


def _lane_daemon(sock_dir, **kw):
    """An in-process LaneScheduler daemon (device-less is fine on CPU:
    lanes resolve against the one visible device)."""
    sock = os.path.join(sock_dir, "kb.sock")
    kw.setdefault("lanes", 0)
    kw.setdefault("microbatch", 2)
    return sock, _start_daemon(sock, **kw)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_lane_crash_answers_structured_error_and_recovers(sock_dir):
    """The injected worker death (a BaseException, like the real
    thing): the claimed request answers a structured error — never a
    wrong plan — the lane restarts, and the next request plans
    byte-identically; the scrape reconciles the incident."""
    sock, (d, t, rc_box) = _lane_daemon(
        sock_dir, faults_spec="lane_crash@1", watchdog_s=5.0
    )
    from kafkabalancer_tpu.serve.lanes import LaneScheduler

    assert isinstance(d._coalescer, LaneScheduler)
    text = open(FIXTURE).read()
    declined = []
    res = sclient.forward_plan(
        sock, ["-no-daemon=true", "-input-json=true"], text,
        on_fallback=declined.append,
    )
    # answered with a structured error (the client would fall back)
    assert res is None
    assert declined and "quarantin" in declined[0]
    # recovery: the next request is served normally, byte-identical
    want_rv, want_out, _ = run_cli(["-input-json", "-no-daemon"], text)
    deadline = time.monotonic() + 10
    res2 = None
    while time.monotonic() < deadline and res2 is None:
        res2 = sclient.forward_plan(
            sock, ["-no-daemon=true", "-input-json=true"], text
        )
        if res2 is None:
            time.sleep(0.2)
    assert res2 is not None
    assert res2.rc == want_rv and res2.stdout == want_out
    doc = sclient.fetch_stats(sock)
    lh = doc["lane_health"]
    assert lh["quarantines"] == 1
    assert lh["recoveries"] == 1
    assert lh["abandoned"] == 1
    assert lh["quarantined"] == []
    adm = doc["admission"]
    assert adm["admitted"] == doc["requests"] + lh["abandoned"]
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0]


def test_wedged_lane_quarantine_requeue_and_recovery():
    """Scheduler-level: a lane wedged mid-request is quarantined by the
    watchdog; its queued-but-unstarted work moves to the healthy lane
    and completes normally (requeued-request parity), its in-flight
    request answers a structured error, and the lane re-admits once the
    stuck call finally returns."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    release = threading.Event()
    handled = []

    def handle(req, coalesced, lane, mb):
        if req.stdin == "WEDGE":
            release.wait(30)
        handled.append((req.stdin, lane.index))
        req.response = {"v": 1, "ok": True, "rc": 0,
                        "stdout": f"plan:{req.stdin}", "stderr": ""}

    lanes = [Lane(0), Lane(1)]
    sched = LaneScheduler(
        handle, lambda _r: None, lanes, watchdog_s=0.3
    )
    try:
        wedge = PlanRequest([], "WEDGE")
        tw = threading.Thread(target=lambda: sched.submit(wedge))
        tw.start()
        time.sleep(0.1)
        wedged_lane = next(
            i for i in range(2) if sched._active[i] > 0
        )
        # pile queued work onto the WEDGED lane directly (routing
        # would avoid it once quarantined; this models work that was
        # already queued when the wedge began)
        q1, q2 = PlanRequest([], "q1"), PlanRequest([], "q2")
        results = {}

        def submit(r):
            results[r.stdin] = sched.submit(r)

        with sched._cv:
            sched._queues[wedged_lane].append(q1)
            sched._queues[wedged_lane].append(q2)
        t1 = threading.Thread(target=submit, args=(q1,))
        t2 = threading.Thread(target=submit, args=(q2,))
        # the waiters' submit() would re-route; emulate the blocked
        # connection threads by waiting on done directly instead
        assert not q1.done.wait(0.0)
        # watchdog: no heartbeat past 0.3 s with active work -> wedge
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not lanes[
            wedged_lane
        ].quarantined:
            sched.health_tick()
            time.sleep(0.05)
        assert lanes[wedged_lane].quarantined
        assert sched.quarantines == 1
        # in-flight answered with a structured error, never a plan
        assert wedge.done.wait(2)
        assert wedge.response["ok"] is False
        assert "quarantined" in wedge.response["error"]
        # queued work requeued onto the healthy lane and completed there
        assert q1.done.wait(5) and q2.done.wait(5)
        assert q1.response["ok"] and q1.response["stdout"] == "plan:q1"
        assert q2.response["ok"] and q2.response["stdout"] == "plan:q2"
        healthy = 1 - wedged_lane
        assert ("q1", healthy) in handled and ("q2", healthy) in handled
        assert sched.requeues == 2 and sched.abandoned == 1
        # recovery: the stuck call returns -> heartbeat -> re-admitted
        release.set()
        tw.join(5)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and lanes[
            wedged_lane
        ].quarantined:
            sched.health_tick()
            time.sleep(0.05)
        assert not lanes[wedged_lane].quarantined
        assert sched.recoveries == 1
        del t1, t2
    finally:
        release.set()
        sched.stop()


def test_all_lanes_quarantined_sheds_instead_of_parking():
    """With EVERY lane quarantined, a new submit must answer a
    structured quarantine shed immediately — parking it on a queue
    nothing drains would hang the client for its whole budget."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    release = threading.Event()

    def handle(req, coalesced, lane, mb):
        release.wait(30)
        req.response = {"v": 1, "ok": True, "rc": 0,
                        "stdout": "x", "stderr": ""}

    lanes = [Lane(0)]
    sched = LaneScheduler(handle, lambda _r: None, lanes, watchdog_s=0.2)
    try:
        wedge = PlanRequest([], "WEDGE")
        tw = threading.Thread(target=lambda: sched.submit(wedge))
        tw.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not lanes[0].quarantined:
            sched.health_tick()
            time.sleep(0.05)
        assert lanes[0].quarantined
        resp = sched.submit(PlanRequest([], "next"))
        assert resp["ok"] is False and resp["op"] == "overload"
        assert resp["reason"] == "quarantine"
        assert resp["retry_after_ms"] >= 1
        release.set()
        tw.join(5)
    finally:
        release.set()
        sched.stop()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_coalescer_dispatcher_death_flushes_and_restarts():
    """Dispatcher-thread death (only a BaseException can do it): the
    popped request and the queue both answer structured errors instead
    of blocking their clients forever, and a fresh loop thread takes
    over."""
    boom = threading.Event()

    def handle(req, coalesced):
        if req.stdin == "BOOM":
            boom.set()
            raise SystemExit("injected dispatcher death")
        req.response = {"v": 1, "ok": True, "rc": 0,
                        "stdout": req.stdin, "stderr": ""}

    c = Coalescer(handle, lambda _r: None)
    try:
        r1, rq = PlanRequest([], "BOOM"), PlanRequest([], "queued")
        res = {}
        t1 = threading.Thread(
            target=lambda: res.__setitem__("r1", c.submit(r1))
        )
        t1.start()
        boom.wait(5)
        # a second request queues behind the dying dispatch
        tq = threading.Thread(
            target=lambda: res.__setitem__("rq", c.submit(rq))
        )
        tq.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and c._thread.is_alive():
            time.sleep(0.02)
        assert not c._thread.is_alive()
        # the popped request already answered through the loop's
        # finally (a structured "request dropped" — never a hang)
        t1.join(5)
        assert res["r1"]["ok"] is False
        logs = []
        c.health_tick(log=logs.append)
        # the queued request is flushed with a structured error
        tq.join(5)
        assert res["rq"]["ok"] is False
        assert "abandoned" in res["rq"]["error"]
        assert c.quarantines == 1 and c.recoveries == 1
        assert c.abandoned >= 1
        assert any("restarted" in m for m in logs)
        # the restarted thread serves normally
        r2 = PlanRequest([], "ok")
        resp = c.submit(r2)
        assert resp["ok"] and resp["stdout"] == "ok"
    finally:
        c.stop()


def test_client_disconnect_mid_plan_daemon_survives(sock_dir):
    """A client that sends a plan and vanishes must not hurt the
    daemon: the request runs, the reply write fails quietly, and the
    next client is served normally."""
    sock = os.path.join(sock_dir, "kb.sock")
    d, t, rc_box = _start_daemon(sock)
    text = open(FIXTURE).read()
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.connect(sock)
    protocol.write_frame(s, {"v": 1, "op": "hello"})
    protocol.read_frame(s)
    protocol.write_frame(s, {
        "v": 1, "op": "plan",
        "argv": ["-no-daemon=true", "-input-json=true"], "stdin": text,
    })
    s.close()  # gone before the answer
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        doc = sclient.fetch_stats(sock)
        if doc is not None and doc["requests"] >= 1:
            break
        time.sleep(0.05)
    res = sclient.forward_plan(
        sock, ["-no-daemon=true", "-input-json=true"], text
    )
    assert res is not None and res.rc == 0
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0]


# --- daemon-level shedding + session interaction --------------------------


def test_daemon_sheds_with_retry_after_and_session_survives(
    sock_dir, monkeypatch
):
    """Flood a window-saturated daemon past -serve-max-queue: the
    overflow answers the structured overload frame (v2 framing
    included), sheds land in serve.shed_s with per-tenant attribution,
    and a resident session that was shed is NOT poisoned — its next
    delta request still hits."""
    sock = os.path.join(sock_dir, "kb.sock")
    d, t, rc_box = _start_daemon(sock, max_queue=1, tenant_inflight=0)
    text = open(FIXTURE).read()
    # register a resident session the normal way (-max-reassign=0: a
    # zero-move plan keeps the resident digest equal to the input, so
    # the repeat below can only delta-hit if the session SURVIVED)
    rv, out0, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-max-reassign=0",
         f"-serve-socket={sock}", "-serve-session=tenant-x"]
    )
    assert rv == 0
    doc0 = sclient.fetch_stats(sock)
    assert doc0["sessions"]["count"] == 1

    # wedge the dispatcher open: every in-daemon run blocks on a latch
    release = threading.Event()
    real_run = cli.run

    def slow_run(i, o, e, args, **kw):
        release.wait(30)
        return real_run(i, o, e, args, **kw)

    monkeypatch.setattr(cli, "run", slow_run)
    window = d._admission.stats()["window"]
    # fill the window (granted) + the 1-slot queue, all slow
    fillers = []
    for i in range(window + 1):
        th = threading.Thread(
            target=sclient.forward_plan,
            args=(sock, ["-no-daemon=true", "-input-json=true"], text),
        )
        th.start()
        fillers.append(th)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st = d._admission.stats()
        if st["granted"] >= window and st["queued"] >= 1:
            break
        time.sleep(0.05)
    # the next arrival must shed: raw v1 exchange shows the frame
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.settimeout(10)
    s.connect(sock)
    protocol.write_frame(s, {"v": 1, "op": "hello"})
    protocol.read_frame(s)
    protocol.write_frame(s, {
        "v": 1, "op": "plan",
        "argv": ["-no-daemon=true", "-input-json=true"], "stdin": text,
    })
    frame = protocol.read_frame(s)
    s.close()
    assert frame["ok"] is False and frame["op"] == "overload"
    assert frame["reason"] == "overload"
    assert frame["retry_after_ms"] >= 1
    release.set()
    for th in fillers:
        th.join(15)
    monkeypatch.setattr(cli, "run", real_run)
    # shed telemetry: its own histogram + counters, request_s untouched
    doc = sclient.fetch_stats(sock)
    assert doc["admission"]["sheds"]["overload"] >= 1
    assert doc["hists"]["serve.shed_s"]["count"] >= 1
    assert doc["hists"]["serve.request_s"]["count"] == doc["requests"]
    # the shed/poison interaction: the resident session still delta-hits
    rv2, out2, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-max-reassign=0",
         f"-serve-socket={sock}", "-serve-session=tenant-x"]
    )
    assert rv2 == 0
    doc2 = sclient.fetch_stats(sock)
    assert doc2["sessions"]["delta_hits"] >= 1
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0]


# --- the client ladder ----------------------------------------------------


class _FakeDaemon:
    """A scripted protocol peer: answers hello like a live daemon,
    then plays a per-plan script ('overload', 'ok', 'hang').
    ``answer_hello=False`` answers only the FIRST connection's hello
    (the handshake) and goes silent for every later one — exactly a
    daemon that wedges after accepting the request, as the client's
    liveness probes see it."""

    def __init__(self, sock_path, script, hello_extra=None,
                 answer_hello=True):
        self.path = sock_path
        self.script = list(script)
        self.plans = 0
        self.conns = 0
        self.answer_hello = answer_hello
        self.hello_extra = hello_extra or {}
        self._listener = socket_mod.socket(
            socket_mod.AF_UNIX, socket_mod.SOCK_STREAM
        )
        self._listener.bind(sock_path)
        self._listener.listen(8)
        self._listener.settimeout(0.2)
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _hello(self):
        return {
            "v": 1, "ok": True, "op": "hello", "pid": os.getpid(),
            "version": __version__, "requests": 0,
            "requests_inflight": 0, "warming": False,
            **self.hello_extra,
        }

    def _serve(self, conn):
        try:
            conn.settimeout(5)
            self.conns += 1
            first_conn = self.conns == 1
            while not self._stop.is_set():
                msg = protocol.read_frame(conn)
                if msg is None:
                    return
                if msg.get("op") == "hello":
                    if not self.answer_hello and not first_conn:
                        return  # silent: the wedge the probe detects
                    protocol.write_frame(conn, self._hello())
                    continue
                if msg.get("op") == "plan":
                    self.plans += 1
                    step = (
                        self.script.pop(0) if self.script else "ok"
                    )
                    if step == "hang":
                        self._stop.wait(30)
                        return
                    if step == "overload":
                        protocol.write_frame(conn, {
                            "v": 1, "ok": False, "op": "overload",
                            "reason": "overload", "retry_after_ms": 20,
                            "error": "request shed (overload)",
                        })
                        continue
                    protocol.write_frame(conn, {
                        "v": 1, "ok": True, "rc": 0,
                        "stdout": "SERVED", "stderr": "",
                    })
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket_mod.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def close(self):
        self._stop.set()
        self._listener.close()
        self._t.join(5)


def test_client_backoff_honors_retry_after_then_succeeds(sock_dir):
    sock = os.path.join(sock_dir, "fd.sock")
    fd = _FakeDaemon(sock, ["overload", "overload", "ok"])
    try:
        notes = []
        t0 = time.monotonic()
        res = sclient.forward_plan(
            sock, ["-no-daemon=true"], "x", note=notes.append
        )
        wall = time.monotonic() - t0
        assert res is not None and res.stdout == "SERVED"
        assert fd.plans == 3  # two sheds, one success, same connection
        assert wall >= 0.02  # at least the retry_after sleeps happened
        assert "overload" not in notes  # it recovered, no fallback
    finally:
        fd.close()


def test_client_overload_gives_up_to_fallback(sock_dir, monkeypatch):
    monkeypatch.setattr(sclient, "RETRY_MAX_ATTEMPTS", 2)
    monkeypatch.setattr(sclient, "RETRY_BACKOFF_CAP_S", 0.05)
    sock = os.path.join(sock_dir, "fd.sock")
    fd = _FakeDaemon(sock, ["overload"] * 10)
    try:
        notes = []
        res = sclient.forward_plan(
            sock, ["-no-daemon=true"], "x", note=notes.append
        )
        assert res is None
        assert notes == ["overload"]
        assert fd.plans == 3  # initial + 2 retries
    finally:
        fd.close()


def test_client_detects_wedged_daemon_in_seconds(sock_dir, monkeypatch):
    """The 3600 s blind wait is gone: a daemon that accepts the plan,
    never answers, and stops answering hello is detected within a few
    progress ticks and attributed daemon_wedged."""
    monkeypatch.setattr(sclient, "PROGRESS_TICK_S", 0.15)
    sock = os.path.join(sock_dir, "fd.sock")
    fd = _FakeDaemon(sock, ["hang"], answer_hello=False)
    try:
        notes = []
        t0 = time.monotonic()
        res = sclient.forward_plan(
            sock, ["-no-daemon=true"], "x", note=notes.append
        )
        wall = time.monotonic() - t0
        assert res is None
        assert notes == ["daemon_wedged"]
        assert wall < 10.0  # seconds, not 3600
    finally:
        fd.close()


def test_client_detects_lost_request(sock_dir, monkeypatch):
    """The daemon stays alive and chatty but holds NO in-flight work
    while we wait: our request was lost — fall back instead of waiting
    out the hour."""
    monkeypatch.setattr(sclient, "PROGRESS_TICK_S", 0.15)
    sock = os.path.join(sock_dir, "fd.sock")
    fd = _FakeDaemon(sock, ["hang"])  # hello fine, plan never answered
    try:
        notes = []
        res = sclient.forward_plan(
            sock, ["-no-daemon=true"], "x", note=notes.append
        )
        assert res is None
        assert notes == ["daemon_wedged"]
    finally:
        fd.close()


def test_client_explicit_timeout_sends_deadline(sock_dir):
    """-serve-client-timeout both bounds the wait and ships the budget
    as deadline_ms in the plan header."""
    sock = os.path.join(sock_dir, "fd.sock")
    seen = {}

    class _Peek(_FakeDaemon):
        def _serve(self, conn):
            try:
                conn.settimeout(5)
                while True:
                    msg = protocol.read_frame(conn)
                    if msg is None:
                        return
                    if msg.get("op") == "hello":
                        protocol.write_frame(conn, self._hello())
                        continue
                    seen.update(msg)
                    self._stop.wait(30)  # never answer the plan
                    return
            except Exception:
                pass

    fd = _Peek(sock, [])
    try:
        notes = []
        t0 = time.monotonic()
        res = sclient.forward_plan(
            sock, ["-no-daemon=true"], "x",
            note=notes.append, client_timeout=0.6,
        )
        wall = time.monotonic() - t0
        assert res is None
        assert notes == ["daemon_wedged"]
        assert 0.4 <= wall < 8.0
        assert 1 <= seen.get("deadline_ms", 0) <= 600
    finally:
        fd.close()


def test_cli_attributes_daemon_wedged_fallback(sock_dir, monkeypatch):
    """End to end through the CLI: the wedge falls back byte-identical
    and lands the serve.fallbacks.daemon_wedged counter in the
    invocation's own metrics export."""
    monkeypatch.setattr(sclient, "PROGRESS_TICK_S", 0.15)
    sock = os.path.join(sock_dir, "fd.sock")
    monkeypatch.setenv("KAFKABALANCER_TPU_SOCKET", sock)
    fd = _FakeDaemon(sock, ["hang"], answer_hello=False)
    try:
        want_rv, want_out, _ = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-no-daemon"]
        )
        mpath = os.path.join(os.path.dirname(sock), "m.json")
        rv, out, _err = run_cli(
            ["-input-json", f"-input={FIXTURE}",
             f"-metrics-json={mpath}"]
        )
        assert rv == want_rv and out == want_out
        with open(mpath) as f:
            payload = json.load(f)
        assert payload["counters"]["serve.fallbacks.daemon_wedged"] == 1
    finally:
        fd.close()


# --- stale-socket takeover ------------------------------------------------


def _make_stale_socket(sock):
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.bind(sock)
    s.close()  # the file stays; connect() now refuses


def test_sigkilled_daemon_leftovers_are_swept(sock_dir):
    """Socket + pidfile left by a SIGKILL'd daemon (pid dead): startup
    sweeps them and serves instead of refusing."""
    sock = os.path.join(sock_dir, "kb.sock")
    _make_stale_socket(sock)
    p = subprocess.Popen(["sleep", "0"])
    p.wait()
    with open(protocol.pidfile_path(sock), "w") as f:
        f.write(f"{p.pid}\n")
    logs = []
    d, t, rc_box = _start_daemon(sock, log=logs.append)
    assert any("swept stale" in m for m in logs)
    assert sclient.daemon_alive(sock) is not None
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0]


def test_zombie_pidfile_process_counts_as_dead():
    """A SIGKILL'd daemon whose parent never reaped it (container
    without an init reaper) is a ZOMBIE: it answers the signal-0 probe
    but cannot own a socket — takeover must treat it as dead."""
    # an UNREAPED child: `sleep 0` exits immediately and stays a
    # zombie of this very process until wait() below (no os.fork — a
    # fork of the JAX-threaded test runner can deadlock the child
    # before it reaches _exit, wedging the whole suite on waitpid)
    p = subprocess.Popen(["sleep", "0"])
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with open(f"/proc/{p.pid}/stat") as f:
                if f.read().rsplit(")", 1)[1].split()[0] == "Z":
                    break
            time.sleep(0.01)
        assert Daemon._pid_alive(p.pid) is False
    finally:
        p.wait()
    assert Daemon._pid_alive(os.getpid()) is True


def test_live_pidfile_process_blocks_takeover(sock_dir):
    """An unresponsive socket whose pidfile process is ALIVE and looks
    like one of our daemons is refused (exit 3), not hijacked."""
    sock = os.path.join(sock_dir, "kb.sock")
    _make_stale_socket(sock)
    # a live process whose cmdline matches a daemon's (the real case:
    # a wedged/mid-start kafkabalancer -serve)
    p = subprocess.Popen([
        sys.executable, "-c", "import time; time.sleep(30)",
        "kafkabalancer -serve (takeover test)",
    ])
    try:
        with open(protocol.pidfile_path(sock), "w") as f:
            f.write(f"{p.pid}\n")
        logs = []
        d = Daemon(sock, warm=False, log=logs.append)
        assert d.serve_forever() == 3
        assert any("refusing to take it over" in m for m in logs)
        assert os.path.exists(sock)  # nothing was swept
    finally:
        p.kill()
        p.wait()


def test_recycled_pid_does_not_block_takeover(sock_dir):
    """PID RECYCLING: the pidfile's pid now belongs to an unrelated
    live process — takeover sweeps and serves instead of demanding
    manual cleanup forever."""
    sock = os.path.join(sock_dir, "kb.sock")
    _make_stale_socket(sock)
    p = subprocess.Popen(["sleep", "30"])  # alive, but not a daemon
    try:
        with open(protocol.pidfile_path(sock), "w") as f:
            f.write(f"{p.pid}\n")
        logs = []
        d, t, rc_box = _start_daemon(sock, log=logs.append)
        assert any("swept stale" in m for m in logs)
        sclient.request_shutdown(sock)
        t.join(15)
        assert rc_box == [0]
    finally:
        p.kill()
        p.wait()


def test_live_daemon_still_refuses_second_daemon(sock_dir):
    sock = os.path.join(sock_dir, "kb.sock")
    d, t, rc_box = _start_daemon(sock)
    d2 = Daemon(sock, warm=False, log=lambda _m: None)
    assert d2.serve_forever() == 3
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0]


# --- scrape schema --------------------------------------------------------


def test_scrape_carries_overload_blocks(sock_dir):
    """serve-stats/8: admission, lane_health and faults blocks are
    present with their golden key sets, and tenant entries carry
    sheds."""
    sock = os.path.join(sock_dir, "kb.sock")
    d, t, rc_box = _start_daemon(sock)
    rv, _out, _err = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
    )
    assert rv == 0
    doc = sclient.fetch_stats(sock)
    golden = json.load(open(os.path.join(
        os.path.dirname(__file__), "data", "serve_stats_schema_v8.json"
    )))
    assert set(doc["admission"]) == set(golden["admission_keys"])
    assert set(doc["lane_health"]) == set(golden["lane_health_keys"])
    assert set(doc["faults"]) == set(golden["faults_keys"])
    assert doc["faults"]["armed"] is None  # inert by default
    assert doc["admission"]["admitted"] == doc["requests"]
    assert doc["admission"]["shed_total"] == 0
    for entry in doc["tenants"]["top"].values():
        assert entry["sheds"] == 0
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0]
