"""Pallas whole-session kernel parity tests (interpreter mode on CPU).

The kernel must reproduce the XLA batched session exactly: same moves in
the same order, same final assignment and loads. Hardware-specific
lowering concerns (Mosaic int8 comparisons, lane→sublane transposes,
MXU matmul precision for integer payloads) are documented in
solvers/pallas_session.py; these tests pin the algorithmic equivalence
that the hardware path is then checked against by bench runs."""

import copy
import random

import pytest

from helpers import random_partition_list

from kafkabalancer_tpu.balancer import balance
from kafkabalancer_tpu.balancer.costmodel import (
    get_bl,
    get_broker_load,
    get_unbalance_bl,
)
from kafkabalancer_tpu.models import default_rebalance_config
from kafkabalancer_tpu.solvers.scan import plan


def unbalance_of(pl):
    return get_unbalance_bl(get_bl(get_broker_load(pl)))


@pytest.mark.parametrize("allow_leader", [False, True])
def test_pallas_session_matches_xla_batch(allow_leader):
    import jax.numpy as jnp

    rng = random.Random(3000 + allow_leader)
    pl = random_partition_list(rng, 40, 8, weighted=True, with_consumers=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-6
    cfg.allow_leader_rebalancing = allow_leader

    pl_x, pl_p = copy.deepcopy(pl), copy.deepcopy(pl)
    opl_x = plan(
        pl_x, copy.deepcopy(cfg), 40, dtype=jnp.float32, batch=16,
        engine="xla",
    )
    # NOTE: XLA batch mode with allow_leader pools leader+follower slots,
    # exactly like the kernel
    opl_p = plan(
        pl_p, copy.deepcopy(cfg), 40, batch=16, engine="pallas-interpret",
    )
    moves_x = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_x.partitions or [])
    ]
    moves_p = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_p.partitions or [])
    ]
    assert moves_x == moves_p
    assert pl_x == pl_p


def test_pallas_session_respects_budget_and_converges():
    rng = random.Random(3100)
    pl = random_partition_list(rng, 30, 6, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-6
    u0 = None
    pl_b = copy.deepcopy(pl)
    opl = plan(pl_b, copy.deepcopy(cfg), 5, batch=8, engine="pallas-interpret")
    assert len(opl) <= 5
    # converged run ends at a true local optimum
    pl_c = copy.deepcopy(pl)
    u0 = unbalance_of(pl_c) if pl_c.partitions[0].weight else None
    plan(pl_c, copy.deepcopy(cfg), 500, batch=8, engine="pallas-interpret")
    assert len(balance(pl_c, copy.deepcopy(cfg))) == 0
    if u0 is not None:
        assert unbalance_of(pl_c) < u0


def test_plan_unknown_engine():
    rng = random.Random(3200)
    pl = random_partition_list(rng, 5, 3, weighted=True)
    with pytest.raises(ValueError, match="unknown engine"):
        plan(pl, default_rebalance_config(), 5, engine="cuda")


@pytest.mark.parametrize("allow_leader", [False, True])
def test_pallas_multi_tile_parity(allow_leader):
    """>TILE_P partitions forces multiple kernel tiles: pins cross-tile
    offset arithmetic, the fori carry, and the global (not per-tile)
    leader-vs-follower tie merge. Equal weights + consumers maximize exact
    ties, the case where merge order is observable."""
    import jax.numpy as jnp

    from kafkabalancer_tpu.solvers.pallas_session import TILE_P

    rng = random.Random(3300 + allow_leader)
    pl = random_partition_list(
        rng, TILE_P + 40, 10, weighted=False, with_consumers=True
    )
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-6
    cfg.allow_leader_rebalancing = allow_leader

    pl_x, pl_p = copy.deepcopy(pl), copy.deepcopy(pl)
    opl_x = plan(
        pl_x, copy.deepcopy(cfg), 25, dtype=jnp.float32, batch=10,
        engine="xla",
    )
    opl_p = plan(
        pl_p, copy.deepcopy(cfg), 25, batch=10, engine="pallas-interpret",
    )
    moves_x = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_x.partitions or [])
    ]
    moves_p = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_p.partitions or [])
    ]
    assert moves_x == moves_p
    assert pl_x == pl_p


def test_plan_unknown_engine_validates_before_mutating():
    """Engine typos raise before any repair mutates the caller's list."""
    from test_balancer import P, wrap

    pl = wrap([P("a", 1, [1, 2, 3], weight=1.0, num_replicas=2)])
    before = copy.deepcopy(pl)
    with pytest.raises(ValueError, match="unknown engine"):
        plan(pl, default_rebalance_config(), 5, engine="palas")
    assert pl == before


def test_pallas_session_restricted_brokers_parity():
    """Per-partition broker restrictions exercise the kernel's allowed-
    matrix branch (the default all-allowed instances take the matrix-free
    fast path since the all_allowed optimization)."""
    import jax.numpy as jnp

    rng = random.Random(3100)
    pl = random_partition_list(
        rng, 40, 8, weighted=True, restrict_brokers=True
    )
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-6

    pl_x, pl_p = copy.deepcopy(pl), copy.deepcopy(pl)
    opl_x = plan(
        pl_x, copy.deepcopy(cfg), 40, dtype=jnp.float32, batch=16,
        engine="xla",
    )
    opl_p = plan(
        pl_p, copy.deepcopy(cfg), 40, batch=16, engine="pallas-interpret",
    )
    moves_x = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_x.partitions or [])
    ]
    moves_p = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_p.partitions or [])
    ]
    assert moves_x == moves_p
    assert pl_x == pl_p
    # restrictions actually bound the plan: every replica stays allowed
    for p in pl_p.iter_partitions():
        assert set(p.replicas).issubset(set(p.brokers))


def test_pallas_session_high_rf_parity():
    """R bucket of 8 (replication factors up to 6): the transposed-layout
    kernel's per-tile transposes, membership derivation, and payload
    capture must stay bit-identical to the XLA batch path across the
    wider slot axis."""
    import jax.numpy as jnp

    rng = random.Random(3200)
    pl = random_partition_list(
        rng, 48, 10, max_rf=6, weighted=True, with_consumers=True
    )
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-6
    cfg.allow_leader_rebalancing = True

    pl_x, pl_p = copy.deepcopy(pl), copy.deepcopy(pl)
    opl_x = plan(
        pl_x, copy.deepcopy(cfg), 40, dtype=jnp.float32, batch=16,
        engine="xla",
    )
    opl_p = plan(
        pl_p, copy.deepcopy(cfg), 40, batch=16, engine="pallas-interpret",
    )
    moves_x = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_x.partitions or [])
    ]
    moves_p = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_p.partitions or [])
    ]
    assert moves_x == moves_p
    assert pl_x == pl_p
