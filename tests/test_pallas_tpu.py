"""Hardware parity for the whole-session Pallas kernel.

All other Pallas tests run the interpreter on CPU (tests/conftest.py pins
the suite to the virtual CPU mesh); until round 3 the compiled Mosaic
path that produces the headline bench number was exercised only by
bench.py — a kernel regression breaking hardware-only behavior (tie
resolution, VMEM ceilings, the f32-exact integer trick) would have
surfaced as a bad benchmark, not a failing test (VERDICT r2 weak #4).

This test re-execs a child with the harness's CPU pins scrubbed so the
ambient TPU backend (axon) initializes; on machines without a TPU the
child reports so and the test SKIPS. On the bench chip it checks the
documented hardware contract (solvers/pallas_session.py:42-46): the
compiled kernel and the XLA batch path may resolve exact float ties
differently, but move count, final unbalance (f32 round-off) and plan
validity must match.
"""

import json
import os
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "pallas_tpu_worker.py")
_CEILING_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "pallas_ceiling_worker.py")


def _scrubbed_env():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    env.pop("JAX_ENABLE_X64", None)
    return env


# ONE bounded ambient-backend probe shared by every hardware test in this
# module. A machine with libtpu installed but no reachable TPU (or a
# wedged relay — the r5 TCP-blackhole lesson) can sit in backend init for
# many minutes before jax gives up; paying that wait once per worker
# turned the tier-1 suite's no-TPU path from seconds into ~24 minutes of
# skip latency. A healthy attach completes in ~1.3 s remote / ms local,
# so the bound is generous; past it we call the backend absent.
_PROBE_TIMEOUT = 120
_probe_result = []  # memo: [platform-or-None]


def _ambient_platform():
    if not _probe_result:
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                env=_scrubbed_env(),
                capture_output=True,
                text=True,
                timeout=_PROBE_TIMEOUT,
            )
            out = proc.stdout.strip().splitlines()
            _probe_result.append(
                out[-1].lower() if proc.returncode == 0 and out else None
            )
        except subprocess.TimeoutExpired:
            _probe_result.append(None)
    return _probe_result[0]


def _require_ambient_tpu():
    platform = _ambient_platform()
    if platform is None:
        pytest.skip(
            f"ambient backend init failed or exceeded {_PROBE_TIMEOUT}s"
        )
    if "tpu" not in platform and "axon" not in platform:
        pytest.skip(f"ambient platform is {platform!r}, not tpu")


def _run_hw_worker(worker, timeout):
    """Run a hardware child with the harness CPU pins scrubbed so the
    ambient backend (the real TPU, when attached) initializes; the axon
    plugin re-registers via sitecustomize. Skips when the child reports
    no TPU (exit 77)."""
    _require_ambient_tpu()
    env = _scrubbed_env()

    proc = subprocess.run(
        [sys.executable, worker],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if proc.returncode == 77:
        pytest.skip(f"no TPU attached: {proc.stdout.strip()}")
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_pallas_hardware_parity():
    out = _run_hw_worker(_WORKER, 1200)  # two cold Mosaic/XLA compiles
    pal, xla = out["pallas"], out["xla"]
    assert pal["valid"] and xla["valid"], out
    # hardware float reduction order may resolve exact candidate ties
    # differently (the documented kernel caveat), and a divergent
    # trajectory can collapse a different number of superseded writes —
    # counts must agree to a small margin, not exactly
    assert abs(pal["n_moves"] - xla["n_moves"]) <= max(
        2, xla["n_moves"] // 50
    ), out
    # f32 session round-off: both converge the same neighborhood; the
    # final objective may differ only at noise level relative to scale
    assert pal["unbalance"] == pytest.approx(
        xla["unbalance"], rel=0.05, abs=1e-6
    ), out


def test_pallas_hardware_ceilings():
    """VERDICT r3 #6: the kernel's documented capacity ceilings
    (solvers/scan.py PALLAS_VMEM_CELLS[_RESTRICTED]) and its batched-tie
    behavior at >= 10k partitions, exercised as budget-capped sessions on
    the bench chip — a Mosaic VMEM regression at the 128k x 256 or
    restricted 64k x 128 buckets now fails a test instead of a benchmark.
    The worker asserts the gate math, the all-allowed/restricted mode
    selection, and plan validity; this parent checks the cross-engine
    tie-storm contract."""
    out = _run_hw_worker(_CEILING_WORKER, 1800)  # three cold compiles
    assert out["ceiling_all_allowed"]["valid"], out
    assert out["ceiling_all_allowed"]["n_moves"] > 0, out
    assert out["ceiling_restricted"]["valid"], out
    assert out["ceiling_restricted"]["n_moves"] > 0, out
    ts = out["tie_storm"]
    pal, xla = ts["pallas"], ts["xla"]
    assert pal["valid"] and xla["valid"], out
    # equal weights: nearly every candidate is an exact f32 tie; counts
    # and objective must agree to the documented hardware margins even
    # when logs diverge on tie resolution
    assert abs(pal["n_moves"] - xla["n_moves"]) <= max(
        2, xla["n_moves"] // 50
    ), out
    assert pal["unbalance"] == pytest.approx(
        xla["unbalance"], rel=0.05, abs=1e-6
    ), out


_F64_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "f64_tpu_worker.py")


def test_f64_paths_on_hardware():
    """Every f64 device path compiles and runs on the REAL chip
    (tests/f64_tpu_worker.py): the r5 sweep failure showed a whole class
    of backend-specific f64 lowering bugs (the u64 bitcast rewrite) can
    hide behind an f32-only benchmark surface — this worker keeps the
    parity-mode dtype covered on hardware every round."""
    # ~6 distinct cold f64 compiles (f64 is software-emulated, ~2x
    # executable size); the sibling tests budget 600s/cold compile
    _run_hw_worker(_F64_WORKER, timeout=3000)
