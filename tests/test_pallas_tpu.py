"""Hardware parity for the whole-session Pallas kernel.

All other Pallas tests run the interpreter on CPU (tests/conftest.py pins
the suite to the virtual CPU mesh); until round 3 the compiled Mosaic
path that produces the headline bench number was exercised only by
bench.py — a kernel regression breaking hardware-only behavior (tie
resolution, VMEM ceilings, the f32-exact integer trick) would have
surfaced as a bad benchmark, not a failing test (VERDICT r2 weak #4).

This test re-execs a child with the harness's CPU pins scrubbed so the
ambient TPU backend (axon) initializes; on machines without a TPU the
child reports so and the test SKIPS. On the bench chip it checks the
documented hardware contract (solvers/pallas_session.py:42-46): the
compiled kernel and the XLA batch path may resolve exact float ties
differently, but move count, final unbalance (f32 round-off) and plan
validity must match.
"""

import json
import os
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "pallas_tpu_worker.py")


def test_pallas_hardware_parity():
    env = dict(os.environ)
    # scrub the conftest/test-harness CPU pins so the child sees the
    # ambient backend; the axon plugin re-registers via sitecustomize
    env.pop("JAX_PLATFORMS", None)
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    )
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    env.pop("JAX_ENABLE_X64", None)

    proc = subprocess.run(
        [sys.executable, _WORKER],
        env=env,
        capture_output=True,
        text=True,
        timeout=1200,  # two cold Mosaic/XLA session compiles
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if proc.returncode == 77:
        pytest.skip(f"no TPU attached: {proc.stdout.strip()}")
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    pal, xla = out["pallas"], out["xla"]
    assert pal["valid"] and xla["valid"], out
    # hardware float reduction order may resolve exact candidate ties
    # differently (the documented kernel caveat), and a divergent
    # trajectory can collapse a different number of superseded writes —
    # counts must agree to a small margin, not exactly
    assert abs(pal["n_moves"] - xla["n_moves"]) <= max(
        2, xla["n_moves"] // 50
    ), out
    # f32 session round-off: both converge the same neighborhood; the
    # final objective may differ only at noise level relative to scale
    assert pal["unbalance"] == pytest.approx(
        xla["unbalance"], rel=0.05, abs=1e-6
    ), out
