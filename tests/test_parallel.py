"""Parallel-layer tests on the 8-virtual-device CPU mesh (conftest.py).

- mesh construction/factorization
- partition-sharded candidate scoring == unsharded scoring (incl. ties)
- what-if sweeps vs per-scenario sequential host runs
"""

import copy
import random

import jax.numpy as jnp
import numpy as np
import pytest

from helpers import random_partition_list

from kafkabalancer_tpu.balancer import balance
from kafkabalancer_tpu.balancer.costmodel import (
    get_bl,
    get_broker_load,
    get_unbalance_bl,
)
from kafkabalancer_tpu.cli import apply_assignment
from kafkabalancer_tpu.models import default_rebalance_config
from kafkabalancer_tpu.ops import tensorize
from kafkabalancer_tpu.parallel.mesh import balanced_factors, make_mesh
from kafkabalancer_tpu.parallel.shard_move import sharded_score_moves
from kafkabalancer_tpu.parallel.sweep import best_scenario, sweep
from kafkabalancer_tpu.solvers.tpu import _oracle_loads, score_moves


def test_balanced_factors():
    assert balanced_factors(8) == (2, 4)
    assert balanced_factors(16) == (4, 4)
    assert balanced_factors(7) == (1, 7)
    assert balanced_factors(1) == (1, 1)


def test_make_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.shape["sweep"] == 2 and mesh.shape["part"] == 4
    mesh = make_mesh(8, shape=(8, 1))
    assert mesh.shape["sweep"] == 8
    with pytest.raises(ValueError):
        make_mesh(10**9)
    with pytest.raises(ValueError):
        make_mesh(8, shape=(3, 2))


@pytest.mark.parametrize("leaders", [False, True])
def test_sharded_score_matches_unsharded(leaders):
    rng = random.Random(900 + leaders)
    cfg = default_rebalance_config()
    mesh = make_mesh(8, shape=(2, 4))
    for _ in range(4):
        pl = random_partition_list(
            rng, rng.randint(4, 30), rng.randint(3, 9),
            weighted=bool(rng.getrandbits(1)), with_consumers=True,
            filled=True,
        )
        dp = tensorize(pl, cfg, min_bucket=8)
        loads_map = _oracle_loads(pl, cfg)
        loads = np.zeros(dp.bvalid.shape[0])
        for bid, load in loads_map.items():
            loads[dp.broker_index(bid)] = load

        args = (
            jnp.asarray(loads), jnp.asarray(dp.replicas),
            jnp.asarray(dp.allowed), jnp.asarray(dp.member),
            jnp.asarray(dp.weights), jnp.asarray(dp.nrep_cur),
            jnp.asarray(dp.nrep_tgt), jnp.asarray(dp.pvalid),
            jnp.asarray(dp.bvalid), float(dp.nb), 2,
        )
        u0, i0, su0, perm0 = score_moves(*args, leaders=leaders)
        u1, i1, su1, perm1 = sharded_score_moves(*args, leaders=leaders, mesh=mesh)
        assert bool(jnp.isinf(u0)) == bool(jnp.isinf(u1))
        if not bool(jnp.isinf(u0)):
            assert float(u0) == float(u1)
            assert int(i0) == int(i1)
        assert float(su0) == float(su1)
        assert (np.asarray(perm0) == np.asarray(perm1)).all()


def test_sharded_tie_break_across_shards():
    """Mirror-image partitions in different shards produce exactly tied
    candidates; the combine must keep the lowest global index."""
    from test_balancer import P, wrap

    cfg = default_rebalance_config()
    # two identical heavy partitions far apart in the partition list
    parts = [P("a", 1, [1, 2], weight=2.0)]
    parts += [P("pad", i, [1, 2], weight=1.0) for i in range(2, 9)]
    parts += [P("z", 1, [1, 2], weight=2.0)]
    parts += [P("t", 1, [3, 4], weight=1.0), P("t", 2, [4, 3], weight=1.0)]
    pl = wrap(parts)
    from kafkabalancer_tpu.balancer.steps import fill_defaults

    fill_defaults(pl, cfg)
    dp = tensorize(pl, cfg, min_bucket=8)
    loads_map = _oracle_loads(pl, cfg)
    loads = np.zeros(dp.bvalid.shape[0])
    for bid, load in loads_map.items():
        loads[dp.broker_index(bid)] = load
    args = (
        jnp.asarray(loads), jnp.asarray(dp.replicas), jnp.asarray(dp.allowed),
        jnp.asarray(dp.member), jnp.asarray(dp.weights),
        jnp.asarray(dp.nrep_cur), jnp.asarray(dp.nrep_tgt),
        jnp.asarray(dp.pvalid), jnp.asarray(dp.bvalid), float(dp.nb), 2,
    )
    mesh = make_mesh(8, shape=(1, 8))
    u0, i0, _, _ = score_moves(*args, leaders=False)
    u1, i1, _, _ = sharded_score_moves(*args, leaders=False, mesh=mesh)
    assert float(u0) == float(u1)
    assert int(i0) == int(i1)


def unbalance_of(pl):
    return get_unbalance_bl(get_bl(get_broker_load(pl)))


def sequential_scenario(pl, cfg, brokers, max_moves=200):
    """Host-pipeline reference for one sweep scenario."""
    pl = copy.deepcopy(pl)
    cfg = copy.deepcopy(cfg)
    cfg.brokers = sorted(brokers)
    n = 0
    try:
        while n < max_moves:
            ppl = balance(pl, cfg)
            if len(ppl) == 0:
                break
            for changed in ppl.partitions:
                apply_assignment(pl, changed)
            n += 1
    except Exception:
        return None, None, None
    return pl, n, unbalance_of(pl)


@pytest.mark.parametrize("weighted", [True, False])
def test_sweep_matches_sequential(weighted):
    rng = random.Random(1000 + weighted)
    pl = random_partition_list(rng, 14, 5, weighted=weighted, max_rf=3)
    observed = sorted({b for p in pl.partitions for b in p.replicas})
    cfg = default_rebalance_config()

    scenarios = [
        observed,  # status quo
        observed + [max(observed) + 1],  # add one broker
        observed + [max(observed) + 1, max(observed) + 2],  # add two
        observed[1:],  # remove the first broker (forces evacuation)
    ]
    results = sweep(pl, cfg, scenarios, max_reassign=200)

    for sc, res in zip(scenarios, results):
        seq_pl, seq_n, seq_u = sequential_scenario(pl, cfg, sc)
        if seq_pl is None:
            assert not res.feasible
            continue
        assert res.feasible
        assert res.unbalance == pytest.approx(seq_u, rel=1e-9, abs=1e-12)
        if weighted:
            # no exact ties → identical final assignment
            assert res.replicas == [p.replicas for p in seq_pl.partitions]


def test_sweep_infeasible_scenario():
    """Removing too many brokers leaves RF-2 partitions with nowhere to go."""
    from test_balancer import P, wrap

    pl = wrap(
        [
            P("a", 1, [1, 2], weight=1.0),
            P("a", 2, [2, 1], weight=1.0),
        ]
    )
    cfg = default_rebalance_config()
    results = sweep(pl, cfg, [[1], [1, 2]], max_reassign=50)
    assert not results[0].feasible
    assert results[1].feasible
    assert best_scenario(results) == 1


def test_sweep_does_not_mutate_input():
    rng = random.Random(1100)
    pl = random_partition_list(rng, 8, 4)
    before = copy.deepcopy(pl)
    observed = sorted({b for p in pl.partitions for b in p.replicas})
    sweep(pl, default_rebalance_config(), [observed], max_reassign=10)
    assert pl == before


def test_session_drained_broker_leaves_table():
    """A leader move can drain a broker entirely; the reference's next
    Balance call then drops it from the load table (it vanishes from
    getBrokerLoad's map), shrinking the objective's average divisor. The
    fused session must reproduce that (scan.py dynamic bvalid)."""
    from test_balancer import P, wrap

    from kafkabalancer_tpu.solvers.scan import plan

    parts = [
        # heavy leader alone on broker 5: score sees weight 6, the applied
        # shift is 6*(2+3)=30 — moving it drains broker 5
        P("big", 1, [5, 1], weight=6.0, num_consumers=3),
        P("s", 1, [1, 2], weight=1.0),
        P("s", 2, [2, 3], weight=1.0),
        P("s", 3, [3, 4], weight=1.0),
        P("s", 4, [4, 1], weight=1.0),
    ]
    cfg = default_rebalance_config()
    cfg.allow_leader_rebalancing = True

    pl_g = wrap([p for p in copy.deepcopy(parts)])
    pl_s = wrap([p for p in copy.deepcopy(parts)])
    moved_g = []
    for _ in range(8):
        ppl = balance(pl_g, copy.deepcopy(cfg))
        if len(ppl) == 0:
            break
        for changed in ppl.partitions:
            live = apply_assignment(pl_g, changed)
            moved_g.append((live.topic, live.partition))
    opl = plan(pl_s, copy.deepcopy(cfg), 8)
    moved_s = [(p.topic, p.partition) for p in (opl.partitions or [])]
    assert ("big", 1) in moved_g  # the drain actually happened
    assert moved_s == moved_g
    assert pl_s == pl_g


def test_sweep_contract_errors():
    """Unsupported configurations raise instead of silently diverging."""
    from test_balancer import P, wrap

    from kafkabalancer_tpu.balancer import BalanceError

    pl = wrap([P("a", 1, [1, 2], weight=1.0), P("a", 2, [2, 1], weight=1.0)])
    cfg = default_rebalance_config()

    cfg_rl = copy.deepcopy(cfg)
    cfg_rl.rebalance_leaders = True
    with pytest.raises(BalanceError, match="rebalance_leaders"):
        sweep(pl, cfg_rl, [[1, 2]])

    with pytest.raises(ValueError, match="2\\^20"):
        sweep(pl, cfg, [[1, 2]], max_reassign=(1 << 20) + 1)


def test_sweep_unsettled_input_matches_sequential():
    """VERDICT r4 missing #2: sweeps no longer reject non-repair-settled
    input. A cluster mid-resize (under- AND over-replicated partitions)
    sweeps directly: each scenario settles host-side with the SCENARIO's
    broker set (the repairs a sequential -broker-ids=<scenario> CLI run
    would apply, steps.go:70-113) before its fused session — final
    assignments and objective match the per-scenario sequential pipeline
    runs, and the repairs consume reassignment budget like CLI loop
    iterations."""
    from test_balancer import P, wrap

    pl = wrap(
        [
            # under-replicated: wants a third replica (scenario-dependent
            # target choice)
            P("u", 1, [1, 2], weight=1.3, num_replicas=3),
            P("u", 2, [2, 3], weight=0.7, num_replicas=3),
            # over-replicated: must drop one
            P("o", 1, [1, 2, 3], weight=1.1, num_replicas=2),
            # settled background
            P("s", 1, [3, 1], weight=0.9),
            P("s", 2, [1, 3], weight=1.2),
            P("s", 3, [2, 1], weight=0.8),
        ]
    )
    cfg = default_rebalance_config()
    observed = [1, 2, 3]
    scenarios = [
        observed,
        observed + [4],       # resize onto a new broker
        observed + [4, 5],
        [2, 3, 4],            # drop broker 1 (evacuation + repairs)
    ]
    results = sweep(pl, cfg, scenarios, max_reassign=200)
    for sc, res in zip(scenarios, results):
        seq_pl, seq_n, seq_u = sequential_scenario(pl, cfg, sc)
        if seq_pl is None:
            assert not res.feasible
            continue
        assert res.feasible and res.completed, (sc, res)
        assert res.n_repairs > 0  # the input genuinely needed repairs
        assert res.unbalance == pytest.approx(seq_u, rel=1e-9, abs=1e-12)
        # weighted instance, no exact ties: identical final assignment
        assert res.replicas == [p.replicas for p in seq_pl.partitions], sc

    # a budget that only covers part of the repairs: structurally
    # incomplete, reported as such (repairs consumed the whole budget)
    bounded = sweep(pl, cfg, scenarios[:1], max_reassign=2)[0]
    assert bounded.feasible and not bounded.completed
    assert bounded.n_repairs == 2 and bounded.n_moves == 0


def test_sweep_unsettled_with_configured_empty_broker():
    """r5 review regression: cfg.brokers naming a broker that holds no
    replicas and appears in no scenario must not desync the per-scenario
    broker universe from the shared encoding (the configured broker is a
    valid move target in every universe, steps.go:150-155)."""
    from test_balancer import P, wrap

    pl = wrap(
        [
            P("u", 1, [1, 2], weight=1.2, num_replicas=3),  # unsettled
            P("s", 1, [2, 3], weight=0.8),
            P("s", 2, [3, 1], weight=1.0),
        ]
    )
    cfg = default_rebalance_config()
    cfg.brokers = [1, 2, 3, 9]  # broker 9: configured, empty, unscoped
    results = sweep(pl, cfg, [[1, 2, 3]], max_reassign=100)
    assert results[0].feasible and results[0].completed
    assert results[0].n_repairs > 0
    seq_pl, _n, seq_u = sequential_scenario(pl, cfg, [1, 2, 3])
    assert results[0].unbalance == pytest.approx(seq_u, rel=1e-9, abs=1e-12)
    assert results[0].replicas == [p.replicas for p in seq_pl.partitions]


def test_sweep_evacuations_consume_budget():
    """Each evacuation is one -max-reassign iteration in the reference CLI
    loop; a binding budget limits evacuations and leaves no optimization."""
    from test_balancer import P, wrap

    # three partitions stranded on broker 9 once the scenario drops it
    pl = wrap(
        [
            P("a", 1, [1, 9], weight=1.0),
            P("a", 2, [2, 9], weight=1.0),
            P("a", 3, [3, 9], weight=1.0),
            P("b", 1, [1, 2], weight=1.0),
            P("b", 2, [2, 3], weight=1.0),
        ]
    )
    cfg = default_rebalance_config()
    scenario = [1, 2, 3]  # drop broker 9
    full = sweep(pl, cfg, [scenario], max_reassign=200)[0]
    assert full.n_evacuations == 3

    assert full.completed

    bounded = sweep(pl, cfg, [scenario], max_reassign=2)[0]
    assert bounded.n_evacuations == 2
    assert bounded.n_moves == 0
    assert bounded.feasible and not bounded.completed  # truncated drain
    # two replicas moved off broker 9, one remains
    stranded = sum(1 for reps in bounded.replicas if 9 in reps)
    assert stranded == 1


def test_distributed_helper_surface():
    """Multi-host wrapper: importable, single-process answer is False."""
    from kafkabalancer_tpu.parallel import initialize, is_multi_host

    assert callable(initialize)
    assert is_multi_host() is False


def test_sweep_pallas_engine_matches_xla():
    """The pallas-engine scenario bodies reach the same per-scenario
    quality as the XLA session (interpreter on CPU; float32, batched
    selection — trajectories may differ, final unbalance must agree to
    f32 noise)."""
    rng = random.Random(1600)
    pl = random_partition_list(rng, 14, 5, weighted=True, max_rf=3)
    observed = sorted({b for p in pl.partitions for b in p.replicas})
    cfg = default_rebalance_config()
    scenarios = [
        observed,
        observed + [max(observed) + 1],
        observed[1:],
    ]
    res_x = sweep(pl, cfg, scenarios, max_reassign=200, batch=4)
    res_p = sweep(
        pl, cfg, scenarios, max_reassign=200, batch=4,
        engine="pallas-interpret",
    )
    for rx, rp in zip(res_x, res_p):
        assert rx.feasible == rp.feasible
        assert rx.completed == rp.completed
        assert rx.n_evacuations == rp.n_evacuations
        if rx.feasible:
            assert rp.unbalance == pytest.approx(
                rx.unbalance, rel=1e-4, abs=1e-6
            )


@pytest.mark.parametrize("allow_leader", [False, True])
def test_sharded_session_matches_single_device(allow_leader):
    """The mesh-sharded converge session (parallel/shard_session.py) must
    reproduce the single-device batched session EXACTLY: the cross-shard
    combine key (val, is_leader, partition) is a total order under which
    the unsharded factored_target_best selection is an associative min,
    so move logs and final state are identical, not merely equivalent."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan

    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(8, shape=(1, 8))
    pl_s = synth_cluster(500, 24, rf=3, seed=31, weighted=True)
    pl_1 = synth_cluster(500, 24, rf=3, seed=31, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-7
    cfg.allow_leader_rebalancing = allow_leader
    opl_s = plan_sharded(pl_s, copy.deepcopy(cfg), 4000, mesh, batch=16)
    opl_1 = plan(pl_1, copy.deepcopy(cfg), 4000, batch=16)
    ms = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_s.partitions or [])
    ]
    m1 = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_1.partitions or [])
    ]
    assert ms == m1
    assert pl_s == pl_1


def test_sharded_session_matches_single_device_restricted():
    """Same exactness contract on an instance with PER-PARTITION broker
    restrictions — the sharded session's [P, B] allowed-matrix path (the
    all-allowed detection in _prep_from_dp skips that matrix entirely, so
    all-allowed instances no longer exercise it)."""
    import random as _random

    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    def restricted(seed):
        pl = synth_cluster(200, 16, rf=3, seed=seed, weighted=True)
        rng = _random.Random(seed)
        for p in pl.iter_partitions():
            # half the partitions: restrict to their own replicas plus a
            # random extra half of the universe
            if rng.random() < 0.5:
                extra = [b for b in range(1, 17) if rng.random() < 0.5]
                p.brokers = sorted(set(p.replicas) | set(extra))
        return pl

    mesh = make_mesh(8, shape=(1, 8))
    pl_s, pl_1 = restricted(91), restricted(91)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-7
    opl_s = plan_sharded(pl_s, copy.deepcopy(cfg), 2000, mesh, batch=8)
    opl_1 = plan(pl_1, copy.deepcopy(cfg), 2000, batch=8)
    ms = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_s.partitions or [])
    ]
    m1 = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_1.partitions or [])
    ]
    assert ms == m1
    assert pl_s == pl_1


@pytest.mark.parametrize("allow_leader", [False, True])
def test_sharded_pallas_engine_bit_matches_xla(allow_leader):
    """The Pallas shard body (parallel/shard_kernel.py, interpret mode)
    must reproduce the XLA shard engine's move log BIT-identically at the
    same dtype (float32): same overload_penalty, same masks, same
    lowest-row per-target argmin, same strict-< leader merge and
    winner-only slot recovery."""
    import jax.numpy as jnp

    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(8, shape=(1, 8))
    pl_k = synth_cluster(300, 20, rf=3, seed=47, weighted=True)
    pl_x = synth_cluster(300, 20, rf=3, seed=47, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-7
    cfg.allow_leader_rebalancing = allow_leader
    opl_k = plan_sharded(
        pl_k, copy.deepcopy(cfg), 2000, mesh, batch=16,
        engine="pallas-interpret",
    )
    opl_x = plan_sharded(
        pl_x, copy.deepcopy(cfg), 2000, mesh, batch=16,
        dtype=jnp.float32, engine="xla",
    )
    mk = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_k.partitions or [])
    ]
    mx = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_x.partitions or [])
    ]
    assert mk == mx
    assert pl_k == pl_x
    assert mk  # the session actually planned moves


def test_sharded_pallas_engine_restricted_bit_matches_xla():
    """Pallas shard body parity on per-partition broker restrictions
    (the [P, B] allowed-matrix kernel input)."""
    import random as _random

    import jax.numpy as jnp

    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.utils.synth import synth_cluster

    def restricted(seed):
        pl = synth_cluster(160, 16, rf=3, seed=seed, weighted=True)
        rng = _random.Random(seed)
        for p in pl.iter_partitions():
            if rng.random() < 0.5:
                extra = [b for b in range(1, 17) if rng.random() < 0.5]
                p.brokers = sorted(set(p.replicas) | set(extra))
        return pl

    mesh = make_mesh(8, shape=(1, 8))
    pl_k, pl_x = restricted(73), restricted(73)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-7
    opl_k = plan_sharded(
        pl_k, copy.deepcopy(cfg), 1000, mesh, batch=8,
        engine="pallas-interpret",
    )
    opl_x = plan_sharded(
        pl_x, copy.deepcopy(cfg), 1000, mesh, batch=8,
        dtype=jnp.float32, engine="xla",
    )
    mk = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_k.partitions or [])
    ]
    mx = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_x.partitions or [])
    ]
    assert mk == mx


def test_sharded_session_odd_mesh():
    """Odd part-axis sizes (S=6 on the 8-device host) work end-to-end:
    plan_sharded's min_bucket keeps every power-of-two bucket divisible
    by the axis size, so no P % S ValueError can surface, and plans stay
    bit-identical to the single-device session."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(6, shape=(1, 6))
    pl_s = synth_cluster(250, 18, rf=3, seed=53, weighted=True)
    pl_1 = synth_cluster(250, 18, rf=3, seed=53, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-7
    opl_s = plan_sharded(pl_s, copy.deepcopy(cfg), 1500, mesh, batch=8)
    opl_1 = plan(pl_1, copy.deepcopy(cfg), 1500, batch=8)
    ms = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_s.partitions or [])
    ]
    m1 = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_1.partitions or [])
    ]
    assert ms == m1


def test_sharded_session_chunk_reentry():
    """Chunked sharded sessions re-enter with the mutated assignment and
    still land a valid plan (same contract as plan's chunking)."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded

    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(4, shape=(1, 4))
    pl = synth_cluster(120, 10, rf=2, seed=33, weighted=True)
    # snapshot BEFORE planning — opl entries alias the live partitions, so
    # the meaningful invariant is that every changed partition is emitted
    before = {
        (p.topic, p.partition): tuple(p.replicas)
        for p in pl.iter_partitions()
    }
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-7
    opl = plan_sharded(pl, cfg, 200, mesh, batch=8, chunk_moves=16)
    emitted = {(e.topic, e.partition) for e in (opl.partitions or [])}
    changed = {
        (p.topic, p.partition)
        for p in pl.iter_partitions()
        if tuple(p.replicas) != before[(p.topic, p.partition)]
    }
    assert changed and changed <= emitted
    for entry in opl.partitions or []:
        assert len(set(entry.replicas)) == len(entry.replicas)


def test_sharded_polish_reaches_single_chip_quality():
    """VERDICT r3 missing #3: the sharded path must reach flagship
    quality, not stall at the move-session floor. plan_sharded's polish
    tail (single-device swap/leader-shuffle alternation on the sharded
    session's converged state) lands at the same floor as the
    single-chip plan(polish=True) — orders of magnitude below the
    move-only sharded session on the same instance."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(8, shape=(1, 8))

    def fresh():
        pl = synth_cluster(600, 24, rf=3, seed=4242, weighted=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 0.0
        cfg.allow_leader_rebalancing = True
        return pl, cfg

    pl_m, cfg_m = fresh()
    plan_sharded(pl_m, cfg_m, 6000, mesh, batch=16)
    u_moves = unbalance_of(pl_m)

    pl_s, cfg_s = fresh()
    plan_sharded(pl_s, cfg_s, 6000, mesh, batch=16, polish=True)
    u_shard = unbalance_of(pl_s)

    pl_1, cfg_1 = fresh()
    plan(pl_1, cfg_1, 6000, batch=16, polish=True)
    u_single = unbalance_of(pl_1)

    # polish must beat the move floor decisively and match the
    # single-chip polish floor (same neighborhoods, same acceptance
    # thresholds — trajectories may differ, floors must not)
    assert u_shard < u_moves / 10
    assert u_shard <= u_single * 5 + 1e-12
    assert u_single <= u_shard * 5 + 1e-12


def test_shard_scale_rebalance_leaders_warns_on_delegation():
    """scale=True with rebalance_leaders cannot shard (the fused leader
    session is sequential by contract): it still delegates — identical
    results — but must WARN that the cluster lands on one device."""
    import warnings as _warnings

    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(4, shape=(1, 4))

    def fresh():
        pl = synth_cluster(120, 10, rf=3, seed=77, weighted=True)
        cfg = default_rebalance_config()
        cfg.rebalance_leaders = True
        cfg.min_unbalance = 1e-6
        return pl, cfg

    pl_s, cfg_s = fresh()
    with pytest.warns(UserWarning, match="single-device"):
        opl_s = plan_sharded(pl_s, cfg_s, 200, mesh, batch=4, scale=True)
    pl_1, cfg_1 = fresh()
    opl_1 = plan(pl_1, cfg_1, 200, batch=4)
    assert _move_log(opl_s) == _move_log(opl_1)
    assert pl_s == pl_1


def test_sharded_rebalance_leaders_delegates():
    """plan_sharded with rebalance_leaders delegates to the fused leader
    session and matches plan() exactly (same move log, same final
    state)."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(8, shape=(1, 8))
    pl_s = synth_cluster(200, 12, rf=3, seed=77, weighted=True)
    pl_1 = synth_cluster(200, 12, rf=3, seed=77, weighted=True)
    cfg = default_rebalance_config()
    cfg.rebalance_leaders = True
    cfg.min_unbalance = 1e-6
    opl_s = plan_sharded(pl_s, copy.deepcopy(cfg), 500, mesh, batch=4)
    opl_1 = plan(pl_1, copy.deepcopy(cfg), 500, batch=4)
    ms = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_s.partitions or [])
    ]
    m1 = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_1.partitions or [])
    ]
    assert ms == m1
    assert pl_s == pl_1


def _colo_count_pl(pl):
    import collections

    c = collections.Counter()
    for p in pl.iter_partitions():
        for b in p.replicas:
            c[(p.topic, b)] += 1
    return sum(v - 1 for v in c.values() if v > 1)


def test_sharded_colocation_matches_single_device():
    """VERDICT r4 missing #1: the anti-colocation objective composes
    with sharding. The sharded colocation session's [T, B] counts are
    replicated state (every update derives from the combined candidate
    pool), each shard scores its rows with the ±λ terms, and the combine
    key is unchanged — so move logs must be BIT-identical to the
    single-device colocation session at the same dtype."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    lam = 0.001
    mesh = make_mesh(8, shape=(1, 8))

    def fresh():
        pl = synth_cluster(400, 16, rf=3, seed=5, weighted=True,
                           zipf_topics=True)
        cfg = default_rebalance_config()
        cfg.allow_leader_rebalancing = True
        cfg.min_unbalance = 1e-9
        return pl, cfg

    pl_s, cfg_s = fresh()
    opl_s = plan_sharded(pl_s, cfg_s, 20000, mesh, batch=16,
                         anti_colocation=lam)
    pl_1, cfg_1 = fresh()
    opl_1 = plan(pl_1, cfg_1, 20000, batch=16, anti_colocation=lam)
    ms = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_s.partitions or [])
    ]
    m1 = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_1.partitions or [])
    ]
    assert ms == m1
    assert pl_s == pl_1
    assert ms  # the session actually planned moves


def test_sharded_colocation_polish_reaches_floor():
    """The full composition the r4 verdict asked for: anti-colocation
    through the SHARDED session with the colocation-aware polish tail
    lands the colocation count on the pigeonhole floor and the load
    objective well below the move-only combined session."""
    import collections

    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.utils.synth import synth_cluster

    lam = 0.001
    B = 16
    mesh = make_mesh(8, shape=(1, 8))

    def fresh():
        pl = synth_cluster(400, B, rf=3, seed=5, weighted=True,
                           zipf_topics=True)
        cfg = default_rebalance_config()
        cfg.allow_leader_rebalancing = True
        cfg.min_unbalance = 1e-9
        return pl, cfg

    pl_m, cfg_m = fresh()
    sizes = collections.Counter(p.topic for p in pl_m.iter_partitions())
    floor = sum(max(0, 3 * s - B) for s in sizes.values())
    plan_sharded(pl_m, cfg_m, 20000, mesh, batch=16, anti_colocation=lam)
    u_moves = unbalance_of(pl_m)
    assert _colo_count_pl(pl_m) == floor

    pl_p, cfg_p = fresh()
    plan_sharded(pl_p, cfg_p, 20000, mesh, batch=16, anti_colocation=lam,
                 polish=True)
    assert _colo_count_pl(pl_p) == floor
    assert unbalance_of(pl_p) < u_moves
    for p in pl_p.iter_partitions():
        assert len(set(p.replicas)) == len(p.replicas)


def test_plan_sharded_cfg_colocation_convention():
    """ADVICE r4 #2 + the r5 kernel-colocation update: a cfg-derived
    anti_colocation must NOT raise in plan_sharded, and since BOTH shard
    engines now carry the combined objective, activation is
    engine-independent (the shared anti_colocation_requested predicate:
    active unless batch<=1 or rebalance_leaders) — no engine override,
    no warning."""
    import warnings as _warnings

    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(4, shape=(1, 4))

    def fresh():
        # 400 x 16 zipf: starts ABOVE the pigeonhole colocation floor
        # (c0=1018 vs floor=1008), so activation is observable as a drop
        pl = synth_cluster(400, 16, rf=3, seed=5, weighted=True,
                           zipf_topics=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-9
        cfg.anti_colocation = 0.001
        return pl, cfg

    # cfg-derived + the streaming kernel (interpret off-TPU): ACTIVATES
    # (the r5 kernel carries the ±λ terms), no raise, no warning
    pl_a, cfg_a = fresh()
    c0 = _colo_count_pl(pl_a)
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        plan_sharded(pl_a, cfg_a, 20000, mesh, batch=8,
                     engine="pallas-interpret")
    assert _colo_count_pl(pl_a) < c0

    # cfg-derived + xla engine: activates identically
    pl_b, cfg_b = fresh()
    plan_sharded(pl_b, cfg_b, 20000, mesh, batch=8)
    assert _colo_count_pl(pl_b) < c0

    # cfg-derived + batch=1: deactivates (plans loads only, no raise)
    pl_c, cfg_c = fresh()
    opl = plan_sharded(pl_c, cfg_c, 500, mesh, batch=1)
    assert len(opl) > 0
    # explicit + batch=1: hard error (mirrors plan())
    pl_d, cfg_d = fresh()
    cfg_d.anti_colocation = 0.0
    with pytest.raises(ValueError, match="batch"):
        plan_sharded(pl_d, cfg_d, 500, mesh, batch=1,
                     anti_colocation=0.001)


def test_sharded_colocation_kernel_bit_matches_xla():
    """The streaming shard kernel's anti-colocation mode (r5,
    shard_kernel.py with_colo): move logs bit-identical to the XLA
    shard engine at float32 on a zipf-topic instance — same ±λ terms in
    both passes, same slot recovery including the colocation source
    term."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.utils.synth import synth_cluster

    lam = 0.001
    mesh = make_mesh(8, shape=(1, 8))

    def fresh():
        pl = synth_cluster(400, 16, rf=3, seed=5, weighted=True,
                           zipf_topics=True)
        cfg = default_rebalance_config()
        cfg.allow_leader_rebalancing = True
        cfg.min_unbalance = 1e-9
        return pl, cfg

    pl_k, cfg_k = fresh()
    opl_k = plan_sharded(pl_k, cfg_k, 20000, mesh, batch=16,
                         engine="pallas-interpret", anti_colocation=lam)
    pl_x, cfg_x = fresh()
    opl_x = plan_sharded(pl_x, cfg_x, 20000, mesh, batch=16,
                         dtype=jnp.float32, engine="xla",
                         anti_colocation=lam)
    mk = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_k.partitions or [])
    ]
    mx = [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl_x.partitions or [])
    ]
    assert mk == mx
    assert pl_k == pl_x
    assert mk  # the session actually planned moves
    assert _colo_count_pl(pl_k) < 1018  # colocations actually dropped


# --- the SCALE tier (ISSUE 13): lean state, sharded upload, row-chunked
# scoring — byte parity with the single-device plan throughout ------------


def _restricted_cluster(n, b, seed):
    import random as _random

    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl = synth_cluster(n, b, rf=3, seed=seed, weighted=True)
    rng = _random.Random(seed)
    for p in pl.iter_partitions():
        if rng.random() < 0.5:
            extra = [x for x in range(1, b + 1) if rng.random() < 0.5]
            p.brokers = sorted(set(p.replicas) | set(extra))
    return pl


def _move_log(opl):
    return [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl.partitions or [])
    ]


@pytest.mark.parametrize("seed", [211, 212, 213])
@pytest.mark.parametrize("restricted", [False, True])
@pytest.mark.parametrize("allow_leader", [False, True])
def test_shard_scale_matches_single_device(seed, restricted, allow_leader):
    """Scale-tier byte parity, the randomized differential pin matrix
    (3 seeds × restricted-brokers × leader-session): plan_sharded with
    scale=True — fine-ladder bucket, lean on-device membership, sharded
    upload, row-chunked scoring — produces the BYTE-identical move log
    and final state of the single-device plan() on the same input."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(8, shape=(1, 8))

    def fresh():
        if restricted:
            pl = _restricted_cluster(160, 12, seed)
        else:
            pl = synth_cluster(160, 12, rf=3, seed=seed, weighted=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-9
        cfg.allow_leader_rebalancing = allow_leader
        return pl, cfg

    pl_s, cfg_s = fresh()
    # row_chunk=8 forces many chunks per shard (the combine actually
    # exercises), and the 160-row instance rides the fine ladder's
    # power-of-two leg — the ladder switch itself is pinned in test_ops
    opl_s = plan_sharded(
        pl_s, cfg_s, 600, mesh, batch=8, scale=True, row_chunk=8
    )
    pl_1, cfg_1 = fresh()
    opl_1 = plan(pl_1, cfg_1, 600, batch=8)
    assert _move_log(opl_s) == _move_log(opl_1)
    assert pl_s == pl_1
    assert len(opl_s) > 0  # the session actually planned moves


def test_shard_scale_row_chunk_invariant():
    """The chunked scorer's combine is exact: any row_chunk (including
    the unchunked 0) yields the identical plan."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(8, shape=(1, 8))

    def one(rc):
        pl = synth_cluster(300, 20, rf=3, seed=47, weighted=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-7
        cfg.allow_leader_rebalancing = True
        opl = plan_sharded(
            pl, cfg, 1500, mesh, batch=16, scale=True, row_chunk=rc
        )
        return _move_log(opl)

    base = one(0)
    assert base
    for rc in (8, 13, 64):
        assert one(rc) == base, rc


def test_shard_scale_psum_load_table_and_argmin_vs_oracle():
    """The differential pins behind the scale tier's determinism
    contract, against the scalar oracle (balancer/steps.py):

    - the sharded session's broker-LOAD table after k accepted moves is
      BIT-identical to the single-device session's (the psum'd integer
      counts and the replicated float loads never drift across shards,
      chunked scoring included), and matches the oracle-side chunked
      replay (steps.replay_broker_loads) of its own move log;
    - the sharded argmin's first accepted move IS the scalar
      scan_moves winner (follower scan: the session scores leader
      moves with their true applied delta where the reference's scan
      deliberately under-models them — scan.py module docstring — so
      the leader axis is pinned by the plan-level byte parity above,
      not by this oracle).

    3 seeds × plain/restricted-brokers, faked 8-device CPU mesh.
    """
    import jax.numpy as jnp

    from kafkabalancer_tpu.balancer import costmodel
    from kafkabalancer_tpu.balancer.steps import (
        fill_defaults,
        replay_broker_loads,
        scan_moves,
    )
    from kafkabalancer_tpu.ops import cost
    from kafkabalancer_tpu.parallel.shard_session import sharded_session
    from kafkabalancer_tpu.solvers.scan import _cfg_broker_mask, session
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(8, shape=(1, 8))
    for seed in (31, 32, 33):
        for restricted in (False, True):
            if restricted:
                pl = _restricted_cluster(120, 10, seed)
            else:
                pl = synth_cluster(120, 10, rf=3, seed=seed, weighted=True)
            cfg = default_rebalance_config()
            cfg.min_unbalance = 1e-9
            fill_defaults(pl, cfg)
            dp = tensorize(pl, cfg, min_bucket=64)
            B = dp.bvalid.shape[0]
            dtype = jnp.float64
            w = jnp.asarray(dp.weights).astype(dtype)
            nc = jnp.asarray(dp.ncons).astype(dtype)
            loads0 = cost.broker_loads(
                jnp.asarray(dp.replicas), w, jnp.asarray(dp.nrep_cur),
                nc, B,
            )
            common = (
                loads0, jnp.asarray(dp.replicas), jnp.asarray(dp.member),
                jnp.asarray(dp.allowed), w, jnp.asarray(dp.nrep_cur),
                jnp.asarray(dp.nrep_tgt), nc, jnp.asarray(dp.pvalid),
                jnp.asarray(_cfg_broker_mask(dp, cfg)),
                jnp.asarray(dp.bvalid),
                jnp.int32(cfg.min_replicas_for_rebalancing),
                jnp.asarray(cfg.min_unbalance, dtype),
                jnp.int32(12),
                jnp.asarray(1.5, dtype),
            )
            out_1 = session(
                *common, max_moves=128, allow_leader=False, batch=8,
            )
            out_s = sharded_session(
                *common, max_moves=128, allow_leader=False, batch=8,
                mesh=mesh, engine="xla", row_chunk=4,
            )
            n1, ns = int(out_1[2]), int(out_s[2])
            assert ns == n1 > 0
            # move logs bit-identical
            for k in (3, 4, 5, 6):
                np.testing.assert_array_equal(
                    np.asarray(out_s[k]), np.asarray(out_1[k]), str(k)
                )
            # the psum'd/replicated broker-load table: bit-identical to
            # the single-device session's
            loads_1 = np.asarray(out_1[1])
            loads_s = np.asarray(out_s[1])
            assert loads_s.tobytes() == loads_1.tobytes()
            # ... and to the oracle-side chunked replay of the move log
            mp = np.asarray(out_s[3])
            mslot = np.asarray(out_s[4])
            msrc = np.asarray(out_s[5])
            mtgt = np.asarray(out_s[6])
            moves = []
            for i in range(ns):
                p, slot = int(mp[i]), int(mslot[i])
                delta = (
                    dp.weights[p] * (dp.nrep_cur[p] + dp.ncons[p])
                    if slot == 0
                    else dp.weights[p]
                )
                moves.append((int(msrc[i]), int(mtgt[i]), delta))
            bl0 = [[b, float(np.asarray(loads0)[b])] for b in range(B)]
            replayed = np.asarray(
                [cell[1] for cell in replay_broker_loads(bl0, moves)]
            )
            np.testing.assert_array_equal(replayed, loads_s)
            # the sharded argmin's first move == the scalar scan winner
            loads_map = costmodel.get_broker_load(pl)
            bl = costmodel.get_bl(loads_map)
            su = costmodel.get_unbalance_bl(bl)
            _cu, best, _pos = scan_moves(
                list(pl.iter_partitions()), bl, su, None, cfg, False
            )
            assert best is not None
            first_part = dp.partitions[int(mp[0])]
            assert (first_part.topic, first_part.partition) == (
                best[0].topic, best[0].partition,
            ), (seed, restricted)
            assert int(dp.broker_ids[int(msrc[0])]) == best[1]
            assert int(dp.broker_ids[int(mtgt[0])]) == best[2]


def test_shard_scale_100k_partition_parity():
    """The acceptance pin: a 100k-partition plan on the faked 8-device
    CPU mesh — fine-ladder bucket (100032 rows vs the doubling ladder's
    131072), lean membership, sharded upload, row-chunked scoring — is
    byte-identical to the single-device plan of the same input."""
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    mesh = make_mesh(8, shape=(1, 8))

    def fresh():
        pl = synth_cluster(100_000, 16, rf=2, seed=7, weighted=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-7
        return pl, cfg

    pl_s, cfg_s = fresh()
    opl_s = plan_sharded(
        pl_s, cfg_s, 128, mesh, batch=64, scale=True, row_chunk=4096
    )
    pl_1, cfg_1 = fresh()
    opl_1 = plan(pl_1, cfg_1, 128, batch=64)
    log_s, log_1 = _move_log(opl_s), _move_log(opl_1)
    assert len(log_s) == 128  # the budget-bound plan really planned
    assert log_s == log_1
    assert pl_s == pl_1


def test_plan_sharded_auto_engine_rule(monkeypatch):
    """plan_sharded's engine="auto" rule (r5): off-TPU it resolves to
    the XLA shard body; on TPU it picks the streaming Mosaic kernel —
    the shard_map-wrapped XLA session crashes the v5e worker at
    >= 131072 x 256 buckets (measured, reproduced), so the kernel owns
    the sharded path by survival — INCLUDING with an activating
    anti-colocation penalty (the kernel carries the combined objective
    since late r5); only an explicit non-f32 dtype forces XLA."""
    import kafkabalancer_tpu.parallel.shard_session as ss
    from kafkabalancer_tpu.utils.synth import synth_cluster

    captured = []
    real = ss.sharded_session

    def spy(*args, **kw):
        captured.append(kw.get("engine"))
        return real(*args, **kw)

    monkeypatch.setattr(ss, "sharded_session", spy)

    mesh = make_mesh(2, shape=(1, 2))

    def fresh():
        pl = synth_cluster(60, 8, rf=2, seed=5, weighted=True,
                           zipf_topics=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-7
        return pl, cfg

    # off-TPU (the CPU test platform): auto -> xla
    pl, cfg = fresh()
    ss.plan_sharded(pl, cfg, 50, mesh, batch=4)
    assert captured[-1] == "xla"

    # mocked TPU mesh platform: auto -> the streaming kernel... which
    # cannot actually run on CPU, so assert the RESOLUTION via the
    # error path. The discriminator is the MESH's devices (a virtual
    # CPU mesh on a TPU host must resolve xla), so mock the mesh.
    class FakeDev:
        platform = "tpu"
        process_index = 0

    class FakeFlat:
        flat = [FakeDev()]

    class FakeMesh:
        devices = FakeFlat()
        shape = dict(mesh.shape)

    pl, cfg = fresh()
    with pytest.raises(Exception, match="pallas"):
        ss.plan_sharded(pl, cfg, 50, FakeMesh(), batch=4)

    # mocked TPU mesh + activating colocation: STILL the kernel (it
    # carries the combined objective since late r5)
    pl, cfg = fresh()
    with pytest.raises(Exception, match="pallas"):
        ss.plan_sharded(pl, cfg, 50, FakeMesh(), batch=4,
                        anti_colocation=0.001)

    # off-TPU + activating colocation: xla (the platform, not the
    # objective, decides)
    pl, cfg = fresh()
    ss.plan_sharded(pl, cfg, 50, mesh, batch=4, anti_colocation=0.001)
    assert captured[-1] == "xla"

    # explicit f64 request: auto honors the precision (kernel is f32)
    pl, cfg = fresh()
    ss.plan_sharded(pl, cfg, 50, mesh, batch=4, dtype=jnp.float64)
    assert captured[-1] == "xla"


def test_plan_sharded_crash_bucket_delegates(monkeypatch):
    """The crash-bucket guard (r5): an explicit XLA shard request on a
    TPU mesh at >= 131072 x 256 buckets must DELEGATE to the single-chip
    session with a warning — the shard_map XLA body kills the TPU worker
    there with no catchable exception, so the route is decided before
    dispatch. Pure-CPU test: the mesh platform is mocked, plan() is
    captured, and no device work runs."""
    import kafkabalancer_tpu.solvers.scan as scan_mod
    import kafkabalancer_tpu.parallel.shard_session as ss
    from kafkabalancer_tpu.models import Partition, PartitionList

    # 140k partitions -> P bucket 262144 (> the 131072-bucket threshold
    # with B bucket 256); replicas spread over 250 brokers
    parts = [
        Partition(
            topic=f"t{i // 64}", partition=i % 64,
            replicas=[1 + (i % 250), 1 + ((i + 97) % 250)],
            weight=1.0,
        )
        for i in range(140_000)
    ]
    pl = PartitionList(version=1, partitions=parts)
    cfg = default_rebalance_config()

    captured = {}

    def fake_plan(pl_, cfg_, budget, **kw):
        captured.update(kw, budget=budget)
        from kafkabalancer_tpu.models.partition import empty_partition_list

        return empty_partition_list()

    monkeypatch.setattr(scan_mod, "plan", fake_plan)

    class FakeDev:
        platform = "tpu"
        process_index = 0

    class FakeFlat:
        flat = [FakeDev()]

    class FakeMesh:
        devices = FakeFlat()
        shape = {"sweep": 1, "part": 1}

    with pytest.warns(UserWarning, match="delegating"):
        ss.plan_sharded(pl, cfg, 1000, FakeMesh(), batch=8, engine="xla")
    assert captured["engine"] == "xla"
    assert captured["budget"] == 1000
    # the delegated run defaults to f32 (plain f64 also exceeds the
    # chip at crash buckets); an explicit dtype passes through
    assert captured["dtype"] == jnp.float32
    captured.clear()
    with pytest.warns(UserWarning, match="delegating"):
        ss.plan_sharded(
            pl, cfg, 1000, FakeMesh(), batch=8, engine="xla",
            dtype=jnp.float64,
        )
    assert captured["dtype"] == jnp.float64
