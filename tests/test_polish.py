"""Pair-swap polish (solvers/polish.py): quality, invariants, budget.

The swap neighborhood is an extension beyond the reference (upstream lists
N-way swaps as planned but never built, README.md:94-100), so there is no
oracle to match; these tests pin the safety invariants (valid replica
sets, budget, monotone improvement) and the quality gain over the
single-move session on instances where the local optimum is strict.
"""

import jax.numpy as jnp
import pytest

from kafkabalancer_tpu.balancer.costmodel import (
    get_bl,
    get_broker_load,
    get_unbalance_bl,
)
from kafkabalancer_tpu.models import default_rebalance_config
from kafkabalancer_tpu.solvers.polish import entry_table
from kafkabalancer_tpu.solvers.scan import plan
from kafkabalancer_tpu.utils.synth import synth_cluster


def u_of(pl):
    return get_unbalance_bl(get_bl(get_broker_load(pl)))


def fresh(n_parts=200, n_brokers=12, seed=7):
    pl = synth_cluster(n_parts, n_brokers, rf=3, seed=seed, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 0.0
    return pl, cfg


@pytest.mark.parametrize("engine", ["xla", "pallas-interpret"])
def test_polish_beats_single_move_optimum(engine):
    pl_plain, cfg = fresh()
    plan(pl_plain, cfg, 100_000, batch=8, engine="xla", polish=False)
    u_plain = u_of(pl_plain)

    pl, cfg = fresh()
    plan(pl, cfg, 100_000, batch=8, engine=engine, polish=True)
    u_pol = u_of(pl)

    # the 200x12 instance has a strict single-move local optimum; swaps
    # must escape it (observed ~6x; assert a conservative margin)
    assert u_pol < u_plain
    assert u_pol < u_plain * 0.8


def test_polish_preserves_replica_set_validity():
    pl, cfg = fresh(seed=11)
    before = {
        (p.topic, p.partition): len(p.replicas) for p in pl.iter_partitions()
    }
    plan(pl, cfg, 100_000, batch=8, engine="xla", polish=True)
    for p in pl.iter_partitions():
        # no duplicate brokers within a partition (ValidateReplicas
        # invariant, steps.go:27-36)
        assert len(set(p.replicas)) == len(p.replicas), p
        # swaps/moves never change replica counts
        assert len(p.replicas) == before[(p.topic, p.partition)]
        # every replica stays on an allowed broker
        assert set(p.replicas).issubset(set(p.brokers))


def test_polish_move_log_replays_to_final_state():
    pl, cfg = fresh(seed=13)
    initial = {
        (p.topic, p.partition): list(p.replicas) for p in pl.iter_partitions()
    }
    opl = plan(pl, cfg, 100_000, batch=8, engine="xla", polish=True)
    # opl entries alias the live partitions (CLI main-loop contract,
    # kafkabalancer.go:177-221): every emitted entry reflects the final
    # assignment of its partition
    for entry in opl.partitions:
        key = (entry.topic, entry.partition)
        live = next(
            p
            for p in pl.iter_partitions()
            if (p.topic, p.partition) == key
        )
        assert entry.replicas == live.replicas
    # something actually changed relative to the initial assignment
    assert any(
        list(p.replicas) != initial[(p.topic, p.partition)]
        for p in pl.iter_partitions()
    )


def test_polish_respects_budget():
    pl, cfg = fresh(seed=17)
    opl = plan(pl, cfg, 7, batch=4, engine="xla", polish=True)
    assert len(opl) <= 7

    pl, cfg = fresh(seed=17)
    opl = plan(pl, cfg, 0, batch=4, engine="xla", polish=True)
    assert len(opl) == 0


def test_polish_with_allow_leader_reaches_deep_balance():
    # follower-only balancing floors at the hottest all-leader broker;
    # with leader moves the polished state should be orders of magnitude
    # below the single-move optimum
    pl_plain, cfg = fresh(400, 16, seed=23)
    cfg.allow_leader_rebalancing = True
    plan(pl_plain, cfg, 100_000, batch=8, engine="xla", polish=False)
    u_plain = u_of(pl_plain)

    pl, cfg = fresh(400, 16, seed=23)
    cfg.allow_leader_rebalancing = True
    plan(pl, cfg, 100_000, batch=8, engine="xla", polish=True)
    assert u_of(pl) < u_plain


def test_polish_min_unbalance_gates_swaps():
    # a large threshold suppresses the swap tail entirely: polish output
    # must match the plain session's
    pl_a, cfg = fresh(seed=29)
    cfg.min_unbalance = 10.0
    opl_a = plan(pl_a, cfg, 100_000, batch=8, engine="xla", polish=False)

    pl_b, cfg = fresh(seed=29)
    cfg.min_unbalance = 10.0
    opl_b = plan(pl_b, cfg, 100_000, batch=8, engine="xla", polish=True)
    assert len(opl_a) == len(opl_b) == 0


def test_entry_table_static_structure():
    from kafkabalancer_tpu.balancer import steps as S
    from kafkabalancer_tpu.ops import tensorize

    pl, cfg = fresh(50, 8, seed=31)
    S.validate_weights(pl, cfg)
    S.fill_defaults(pl, cfg)
    dp = tensorize(pl, cfg)
    ew, ep, er, evalid = entry_table(dp, min_replicas=2)
    n = int(evalid.sum())
    # weights ascending over the valid prefix, +inf padding after
    assert (ew[: n - 1] <= ew[1:n]).all()
    assert (ew[n:] == float("inf")).all()
    # follower slots only, within each partition's replica count
    assert (er[:n] >= 1).all()
    for i in range(n):
        assert er[i] < dp.nrep_cur[ep[i]]
    # min-replicas gate (steps.go:168-170)
    assert (dp.nrep_tgt[ep[:n]] >= 2).all()


def test_polish_near_global_optimum_tiny():
    """Exhaustively enumerate every assignment on tiny instances: the
    allow-leader polish pipeline must land within a small factor of the
    true global optimum (greedy+swaps is still local search; the bound
    documents how close it provably gets on these instances)."""
    import itertools

    from kafkabalancer_tpu.models import Partition, PartitionList

    def pen_total(loads, brokers):
        avg = sum(loads[b] for b in brokers) / len(brokers)
        tot = 0.0
        for b in brokers:
            rel = loads[b] / avg - 1.0
            tot += rel * rel * (1.0 if rel > 0 else 0.5)
        return tot

    rng_specs = [
        # (weights per partition, rf), 3 brokers
        ([2.0, 1.1, 0.7, 1.6, 0.9], 1),
        ([1.5, 0.5, 1.2, 0.8], 2),
    ]
    brokers = [1, 2, 3]
    for weights, rf in rng_specs:
        # exhaustive optimum over ordered replica tuples (leader = first)
        choices = [
            list(itertools.permutations(brokers, rf)) for _ in weights
        ]
        best = float("inf")
        for combo in itertools.product(*choices):
            loads = {b: 0.0 for b in brokers}
            for w, reps in zip(weights, combo):
                loads[reps[0]] += w * len(reps)  # leader premium (ncons=0)
                for b in reps[1:]:
                    loads[b] += w
            best = min(best, pen_total(loads, brokers))

        pl = PartitionList(
            version=1,
            partitions=[
                Partition(
                    topic="t", partition=i, replicas=list(brokers[:rf]),
                    weight=w,
                )
                for i, w in enumerate(weights)
            ],
        )
        cfg = default_rebalance_config()
        cfg.min_unbalance = 0.0
        cfg.allow_leader_rebalancing = True
        cfg.brokers = list(brokers)  # full universe incl. unobserved
        plan(pl, cfg, 10_000, batch=2, engine="xla", polish=True)
        got = u_of(pl)
        assert got <= max(best * 3.0, best + 1e-9), (weights, rf, got, best)


def test_nearest_occupied_matches_bruteforce():
    """polish.nearest_occupied must reproduce the brute-force next/prev
    occupied entry EXACTLY for random holders, pair tables and query
    ranks — including dead pairs, empty rows, 128-aligned ranks (the
    boundary case for any future block-decomposed implementation) and
    the rq=0 / rq=Nc edges."""
    import numpy as np

    from kafkabalancer_tpu.solvers.polish import nearest_occupied

    W = 128  # probe block-boundary ranks regardless of implementation

    rng = np.random.default_rng(1234)
    for trial in range(8):
        Nc = int(rng.choice([256, 512, 1024]))
        nh = int(rng.choice([4, 8, 16]))
        B = 16
        holder = rng.integers(0, B + 1, size=Nc).astype(np.int32)
        tgt_b = rng.integers(0, B, size=nh).astype(np.int32)
        pair_live = rng.random(nh) < 0.8
        pe_c = rng.integers(0, nh, size=Nc).astype(np.int32)
        # ranks hit edges and block boundaries on purpose
        rq = np.concatenate(
            [
                rng.integers(0, Nc + 1, size=Nc - 6),
                [0, Nc, W - 1, W, Nc - 1, Nc - W],
            ]
        ).astype(np.int32)[:Nc]

        ja, jb = nearest_occupied(
            jnp.asarray(holder), jnp.asarray(tgt_b),
            jnp.asarray(pair_live), jnp.asarray(pe_c), jnp.asarray(rq)
        )
        ja, jb = np.asarray(ja), np.asarray(jb)

        occ = (holder[None, :] == tgt_b[:, None]) & pair_live[:, None]
        for q in range(Nc):
            row = occ[pe_c[q]]
            start = min(int(rq[q]), Nc - 1)
            idx = np.nonzero(row[start:])[0]
            want_a = start + idx[0] if len(idx) else Nc + 1
            end = min(max(int(rq[q]) - 1, 0), Nc - 1)
            idx = np.nonzero(row[: end + 1])[0]
            want_b = idx[-1] if len(idx) else -1
            assert ja[q] == want_a, (trial, q, ja[q], want_a)
            assert jb[q] == want_b, (trial, q, jb[q], want_b)
