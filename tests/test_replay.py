"""The fleet-churn replay harness (kafkabalancer_tpu/replay/).

Pins:

- the synthesizer is DETERMINISTIC: one seed, one event stream, one
  byte sequence of tenant states — a replay run is a reproducible
  regression gate, not a flaky load test;
- churn events do what they claim (weight drift, broker failure with
  allowlist rewrite, topic storms growing the row set);
- a seeded run against a live daemon produces a replay/5 artifact whose
  per-tenant request counts reconcile EXACTLY with the daemon's
  serve-stats/8 scrape, whose scrape percentiles agree with the flight
  recorder's tenant-labeled request log within one histogram bucket,
  and whose sampled request has plan byte parity vs -no-daemon.
"""

import json
import os
import shutil
import tempfile
import threading
import time

import pytest

from kafkabalancer_tpu.replay import (
    REPLAY_SCHEMA,
    FleetSynth,
    ReplayConfig,
    run_replay,
)
from kafkabalancer_tpu.serve import client as sclient
from kafkabalancer_tpu.serve.daemon import Daemon


# --- synthesizer ----------------------------------------------------------


def _drive(seed: int, steps: int):
    synth = FleetSynth(
        seed,
        tenants=3,
        base_partitions=24,
        brokers=6,
        weight_shift_every=5,
        topic_storm_every=7,
        broker_failure_every=9,
    )
    trail = []
    for step in range(steps):
        tenant, fired = synth.step(step)
        trail.append((tenant.name, tuple(fired), tenant.text()))
    return synth, trail


def test_synth_is_deterministic_per_seed():
    _s1, t1 = _drive(42, 40)
    _s2, t2 = _drive(42, 40)
    assert t1 == t2
    _s3, t3 = _drive(43, 40)
    assert t1 != t3


def test_synth_skewed_sizes_and_valid_states():
    synth = FleetSynth(11, tenants=4, base_partitions=64, brokers=8)
    sizes = [len(t.rows) for t in synth.tenants]
    assert sizes[0] > sizes[-1]  # zipf skew: tenant 0 is the whale
    for t in synth.tenants:
        doc = json.loads(t.text())
        assert doc["version"] == 1
        keys = {(r["topic"], r["partition"]) for r in doc["partitions"]}
        assert len(keys) == len(doc["partitions"])  # unambiguous
        for r in doc["partitions"]:
            assert len(set(r["replicas"])) == len(r["replicas"])
            assert all(0 <= b < 8 for b in r["replicas"])


def test_synth_churn_events_mutate_state():
    synth = FleetSynth(5, tenants=1, base_partitions=24, brokers=8)
    t = synth.tenants[0]
    before = t.text()
    assert t.shift_weights(synth.rng, 0.2) >= 1
    assert t.text() != before
    n_rows = len(t.rows)
    t.topic_storm(synth.rng, 4)
    assert len(t.rows) == n_rows + 4
    failed = t.fail_broker(synth.rng)
    assert failed is not None
    assert failed not in t.brokers
    for row in t.rows:
        assert failed not in row["brokers"]
        assert failed not in row["replicas"]


def test_tenant_apply_plan_closes_the_loop():
    synth = FleetSynth(3, tenants=1, base_partitions=16, brokers=6)
    t = synth.tenants[0]
    row = t.rows[0]
    new = [b for b in range(6) if b not in row["replicas"]][: len(
        row["replicas"]
    )]
    plan = json.dumps({
        "version": 1,
        "partitions": [{
            "topic": row["topic"], "partition": row["partition"],
            "replicas": new,
        }, {"topic": "unknown", "partition": 999, "replicas": [1]}],
    })
    assert t.apply_plan(plan) == 1  # unknown entries ignored
    assert t.rows[0]["replicas"] == new
    assert t.moves_applied == 1


# --- the harness against a live daemon ------------------------------------


@pytest.fixture
def daemon_sock():
    # NOT tmp_path: unix socket paths cap at ~104 bytes
    d0 = tempfile.mkdtemp(prefix="kbr-")
    sock = os.path.join(d0, "kb.sock")
    d = Daemon(sock, idle_timeout=120.0, warm=False, log=lambda _m: None)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    yield sock
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0], rc_box
    shutil.rmtree(d0, ignore_errors=True)


def test_replay_reconciles_against_live_daemon(daemon_sock):
    """The acceptance pin: seeded multi-tenant churn, closed loop
    through the real client — counts exact, latency within one bucket,
    parity on the sampled request, session ladder exercised."""
    cfg = ReplayConfig(
        seed=7, tenants=3, requests=36,
        socket=daemon_sock, spawn=False,
        topic_storm_every=11, broker_failure_every=13,
    )
    art = run_replay(cfg, log=lambda _m: None)
    assert art["schema"] == REPLAY_SCHEMA
    assert art["scrape_schema"] == "kafkabalancer-tpu.serve-stats/8"
    assert art["requests_issued"] == 36
    assert art["request_errors"] == []
    assert art["reconciled_counts"] is True
    assert art["latency_checked"] is True  # fresh daemon, ring not full
    assert art["reconciled_latency"] is True
    assert art["reconciled"] is True
    assert art["parity"] is not None and art["parity"]["ok"] is True
    per = art["per_tenant"]
    assert sorted(per) == ["tenant-00", "tenant-01", "tenant-02"]
    assert sum(e["issued"] for e in per.values()) == 36
    for e in per.values():
        assert e["counts_ok"] and e["latency_ok"]
        assert e["daemon_requests"] == e["issued"]
        assert e["client_covers_daemon"]
    # the churn must actually exercise the session ladder: steady-state
    # delta hits AND at least one resync across the fleet
    assert sum(e.get("delta_hits", 0) for e in per.values()) >= 3
    assert (
        sum(e.get("resyncs_rows", 0) for e in per.values())
        + sum(e.get("resyncs_full", 0) for e in per.values())
    ) >= 1
    assert art["events"]["plan"] == 36
    assert art["events"]["topic_storm"] >= 1
    # replay/5: end-to-end trace-id reconciliation — every served
    # request's daemon flight record carries the client's trace id,
    # exactly (fresh private daemon: the flight ring is complete)
    tr = art["trace"]
    assert tr["checked"] is True
    assert tr["reconciled"] is True
    assert tr["ids_issued"] == 36 and tr["ids_unique"] is True
    assert tr["flight_tagged"] == tr["flight_records"] == 36


def test_replay_artifact_schema_keys(daemon_sock):
    """The replay/5 artifact's top-level keys are the schema bench.py
    lands in BENCH rounds — changing them requires a version bump."""
    cfg = ReplayConfig(
        seed=1, tenants=2, requests=8, socket=daemon_sock, spawn=False,
        parity_sample=False,
    )
    art = run_replay(cfg, log=lambda _m: None)
    assert set(art) == {
        "schema", "scrape_schema", "mode", "chaos", "restart", "watch",
        "seed", "config",
        "requests_issued", "request_errors", "wall_s", "throughput_rps",
        "events", "per_tenant", "session_thrash", "fallback_rate",
        "padded_slots", "microbatched", "tenant_cap", "tenants_demoted",
        "parity", "reconciled_counts", "latency_checked",
        "reconciled_latency", "trace", "reconciled",
    }
    # a churn run marks its mode and carries no chaos/restart/watch block
    assert art["mode"] == "churn"
    assert art["chaos"] is None
    assert art["restart"] is None
    assert art["watch"] is None
    assert art["parity"] is None  # parity_sample=False
    entry = art["per_tenant"]["tenant-00"]
    for key in (
        "issued", "daemon_requests", "counts_ok", "moves_applied",
        "partitions", "client_p50", "client_p95", "client_p99",
        "daemon_p50", "daemon_p95", "daemon_p99", "flight_p50",
        "flight_p95", "flight_p99", "latency_bucket_delta",
        "client_bucket_delta", "client_covers_daemon",
        "latency_checked", "latency_ok",
        "delta_hits", "resyncs_rows", "resyncs_full", "fallbacks",
        "session_bytes", "delta_hit_rate",
    ):
        assert key in entry, key


def test_restart_replay_recovers_from_spill():
    """The session-durability acceptance pin (ISSUE 14): a private
    subprocess daemon with a warm spill dir is SIGKILLed mid-churn and
    restarted on the same socket + spill dir — every answered request
    byte-identical to -no-daemon, every pre-kill tenant's first
    post-restart request answered from a spill restore (restore-hit
    rate 1.0, no re-register), the restore_delay chaos site fired on
    the recovery path, and the warm tier's conservation identity
    exact."""
    cfg = ReplayConfig(
        seed=11, tenants=2, requests=10,
        arrival="uniform",       # both tenants see both phases
        weight_shift_every=0,    # no external drift: digests must match
        restart=True,
    )
    art = run_replay(cfg, log=lambda _m: None)
    assert art["schema"] == REPLAY_SCHEMA
    assert art["mode"] == "restart"
    assert art["scrape_schema"] == "kafkabalancer-tpu.serve-stats/8"
    assert art["request_errors"] == []
    r = art["restart"]
    assert r["ok"] is True and art["reconciled"] is True
    assert r["wrong_plans"] == []
    assert r["answered"] == r["parity_checked"] == 10
    assert r["kill_after"] == 5
    # both tenants had pre-kill traffic; both restored on their first
    # post-restart request with a matching digest — zero re-registers
    assert r["expected_restore_attempts"] == 2
    assert r["restore_attempts_ok"] is True
    assert r["restores"] == r["restore_hits"] == 2
    assert r["restore_hit_rate"] == 1.0
    assert r["corrupt_drops"] == 0 and r["cold_misses_post"] == 0
    assert r["paging_identity_ok"] is True
    assert r["faults_fired_post"].get("restore_delay", 0) == 1
    assert r["post_restart_p95_s"] > 0.0
    per = art["per_tenant"]
    assert sum(e["restores"] for e in per.values()) == 2


def test_restart_replay_corrupt_record_is_cold_but_correct():
    """A seeded spill_corrupt on the pre-kill daemon: the restarted
    daemon must detect the bit-flipped record, prune it
    (corrupt_drops), answer the request via a full re-register — and
    every answer stays byte-identical. Never a wrong plan, only a
    cold miss."""
    cfg = ReplayConfig(
        seed=3, tenants=1, requests=3,
        arrival="uniform", weight_shift_every=0,
        restart=True, restart_kill_after=1,
        chaos_faults="spill_corrupt@1",
    )
    art = run_replay(cfg, log=lambda _m: None)
    r = art["restart"]
    assert r["ok"] is True and r["wrong_plans"] == []
    assert r["corrupt_drops"] == 1
    assert r["restores"] == 0 and r["restore_hits"] == 0
    assert r["cold_misses_post"] == 1  # the re-register it forced
    assert r["paging_identity_ok"] is True
    assert art["request_errors"] == []


def test_watch_replay_zero_client_plan_ops():
    """The watch-mode scenario (ISSUE 15): a private -watch subprocess
    daemon over the fake-ZK seam plans closed-loop — the harness plays
    the operator (applies each emitted plan, injects drift) and never
    issues a plan-family request. Every emitted plan byte-identical to
    -no-daemon on the exact state it was planned from, the steady
    state answered from the speculative memo, and the speculation
    identity exact."""
    cfg = ReplayConfig(seed=7, requests=8, watch=True)
    art = run_replay(cfg, log=lambda _m: None)
    assert art["schema"] == REPLAY_SCHEMA
    assert art["mode"] == "watch"
    assert art["scrape_schema"] == "kafkabalancer-tpu.serve-stats/8"
    assert art["chaos"] is None and art["restart"] is None
    w = art["watch"]
    assert w["ok"] is True and art["reconciled"] is True, w
    assert w["wrong_plans"] == [] and w["oracle_missing"] == 0
    assert w["plans_emitted"] >= 3
    assert w["parity_checked"] == w["plans_emitted"]
    # no client plan ops, ever — the daemon planned on its own
    assert w["zero_client_plan_ops"] is True
    assert art["requests_issued"] == 0
    # the steady state is memo reads
    assert w["spec_hit_plans"] >= 1
    assert w["errors"] == 0
    # drift was injected and noticed
    assert w["drift_events"] >= 1 and w["resyncs"] >= 1
    # exact speculation reconciliation (live memos included)
    s = w["speculation"]
    assert s["attempts"] == (
        s["hits"] + s["misses"] + s["poisoned"] + s["memos"]
    ), s
    assert w["speculation_identity_ok"] is True
    assert w["last_event_lag_s"] is not None


def test_replay_requires_a_daemon():
    from kafkabalancer_tpu.replay import ReplayError

    d0 = tempfile.mkdtemp(prefix="kbr-")
    try:
        cfg = ReplayConfig(
            socket=os.path.join(d0, "absent.sock"), spawn=False,
            requests=2,
        )
        with pytest.raises(ReplayError):
            run_replay(cfg, log=lambda _m: None)
    finally:
        shutil.rmtree(d0, ignore_errors=True)
