"""Fused multi-move session tests.

On weighted instances (no exact candidate ties) the fused device loop must
reproduce the greedy per-move pipeline move for move; on equal-weight
instances ties may resolve differently (scan.py module docstring), so the
assertion weakens to equal move counts and an unbalance trajectory no worse
than the oracle's to float round-off."""

import copy
import random

import pytest

from helpers import random_partition_list

from kafkabalancer_tpu.balancer import balance
from kafkabalancer_tpu.balancer.costmodel import (
    get_bl,
    get_broker_load,
    get_unbalance_bl,
)
from kafkabalancer_tpu.cli import apply_assignment
from kafkabalancer_tpu.models import default_rebalance_config
from kafkabalancer_tpu.solvers.scan import plan


def greedy_session(pl, cfg, max_moves):
    out = []
    for _ in range(max_moves):
        ppl = balance(pl, cfg)
        if len(ppl) == 0:
            break
        for changed in ppl.partitions:
            live = apply_assignment(pl, changed)
            out.append((live.topic, live.partition))
    return out


def unbalance_of(pl):
    return get_unbalance_bl(get_bl(get_broker_load(pl)))


@pytest.mark.parametrize("allow_leader", [False, True])
def test_plan_matches_greedy_weighted(allow_leader):
    rng = random.Random(200 + allow_leader)
    for _ in range(5):
        pl = random_partition_list(
            rng, rng.randint(3, 25), rng.randint(3, 8),
            weighted=True, with_consumers=True,
        )
        cfg = default_rebalance_config()
        cfg.allow_leader_rebalancing = allow_leader
        pl_g, pl_s = copy.deepcopy(pl), copy.deepcopy(pl)
        moved_g = greedy_session(pl_g, copy.deepcopy(cfg), 12)
        opl = plan(pl_s, copy.deepcopy(cfg), 12)
        moved_s = [(p.topic, p.partition) for p in (opl.partitions or [])]
        assert moved_s == moved_g
        assert pl_s == pl_g


def test_plan_equal_weights_quality():
    rng = random.Random(300)
    for _ in range(4):
        pl = random_partition_list(rng, 25, 6, weighted=False)
        cfg = default_rebalance_config()
        pl_g, pl_s = copy.deepcopy(pl), copy.deepcopy(pl)
        moved_g = greedy_session(pl_g, copy.deepcopy(cfg), 20)
        opl = plan(pl_s, copy.deepcopy(cfg), 20)
        assert len(opl) == len(moved_g)
        assert unbalance_of(pl_s) <= unbalance_of(pl_g) + 1e-9


def test_plan_includes_repairs():
    """Head repairs (add/remove replicas) fire host-side first and count
    against the budget, like the CLI main loop."""
    rng = random.Random(400)
    pl = random_partition_list(rng, 10, 5, weighted=True, filled=False)
    # force one under- and one over-replicated partition
    pl.partitions[0].num_replicas = len(pl.partitions[0].replicas) + 1
    pl.partitions[1].replicas = pl.partitions[1].replicas[:1]
    pl.partitions[1].num_replicas = 0  # default → stays 1
    cfg = default_rebalance_config()
    pl_g, pl_s = copy.deepcopy(pl), copy.deepcopy(pl)
    moved_g = greedy_session(pl_g, copy.deepcopy(cfg), 10)
    opl = plan(pl_s, copy.deepcopy(cfg), 10)
    moved_s = [(p.topic, p.partition) for p in (opl.partitions or [])]
    assert moved_s == moved_g
    assert pl_s == pl_g


def test_plan_budget_zero():
    rng = random.Random(500)
    pl = random_partition_list(rng, 5, 3)
    assert len(plan(pl, default_rebalance_config(), 0)) == 0


def test_plan_converged_input_empty():
    from test_balancer import P, wrap

    pl = wrap([P("a", 1, [1, 2], weight=1.0), P("a", 2, [2, 1], weight=1.0)])
    assert len(plan(pl, default_rebalance_config(), 5)) == 0


def test_plan_rebalance_leaders_fallback():
    rng = random.Random(600)
    pl = random_partition_list(rng, 12, 4, weighted=True)
    cfg = default_rebalance_config()
    cfg.rebalance_leaders = True
    pl_g, pl_s = copy.deepcopy(pl), copy.deepcopy(pl)
    moved_g = greedy_session(pl_g, copy.deepcopy(cfg), 8)
    opl = plan(pl_s, copy.deepcopy(cfg), 8)
    moved_s = [(p.topic, p.partition) for p in (opl.partitions or [])]
    assert moved_s == moved_g
    assert pl_s == pl_g


def test_plan_float32_quality():
    """The f32 throughput mode reaches the same unbalance to f32 noise."""
    import jax.numpy as jnp

    rng = random.Random(700)
    pl = random_partition_list(rng, 30, 8, weighted=True)
    cfg = default_rebalance_config()
    pl_g, pl_s = copy.deepcopy(pl), copy.deepcopy(pl)
    greedy_session(pl_g, copy.deepcopy(cfg), 30)
    plan(pl_s, copy.deepcopy(cfg), 30, dtype=jnp.float32)
    assert unbalance_of(pl_s) <= unbalance_of(pl_g) + 1e-4


@pytest.mark.parametrize("batch", [4, 16])
def test_plan_batched_quality(batch):
    """Batched commits converge to the same quality as one-at-a-time greedy
    (broker-disjoint deltas are exactly additive) in fewer iterations."""
    rng = random.Random(800 + batch)
    for weighted in (True, False):
        pl = random_partition_list(rng, 40, 8, weighted=weighted, filled=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-6
        pl_b = copy.deepcopy(pl)
        u_start = unbalance_of(pl_b)
        opl = plan(pl_b, copy.deepcopy(cfg), 200, batch=batch)
        # a different hill-climb trajectory than one-at-a-time greedy (it
        # may reach a different local optimum), but it must (a) improve,
        # (b) stay well-formed, and (c) terminate only at a true local
        # optimum: the greedy pipeline finds no further move either
        assert unbalance_of(pl_b) < u_start
        assert 0 < len(opl) < 200
        for p in opl.partitions:
            assert len(set(p.replicas)) == len(p.replicas)
        assert len(balance(pl_b, copy.deepcopy(cfg))) == 0
        # the churn gate keeps the emitted plan close to the one-at-a-time
        # trajectory's length (each emitted move is real data movement)
        pl_s = copy.deepcopy(pl)
        n_single = len(plan(pl_s, copy.deepcopy(cfg), 200, batch=1))
        assert len(opl) <= 2 * n_single + 5


def test_plan_batched_respects_budget():
    rng = random.Random(850)
    pl = random_partition_list(rng, 30, 6, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-9
    opl = plan(pl, cfg, 5, batch=8)
    assert len(opl) <= 5


def test_batched_move_inflation_bounded():
    """The churn gate keeps the batched trajectory's emitted move count
    within 5% of the batch=1 trajectory at comparable final unbalance
    (VERDICT r1 weak #3: each extra emitted move is real Kafka data
    movement). Swept at 10k x 100 the default gate gives +0.14%; pin the
    5%% contract at a CPU-friendly scale across several instances."""
    from kafkabalancer_tpu.utils.synth import synth_cluster

    for seed in (7, 11, 42):
        counts = {}
        for batch in (1, 16):
            pl = synth_cluster(600, 16, rf=3, seed=seed, weighted=True)
            cfg = default_rebalance_config()
            cfg.min_unbalance = 1e-5
            opl = plan(pl, cfg, 100_000, batch=batch)
            counts[batch] = (len(opl), unbalance_of(pl))
        n1, u1 = counts[1]
        nb, ub = counts[16]
        assert nb <= n1 * 1.05 + 1, (seed, n1, nb)
        # comparable quality: the batched run converges at least as deep
        # up to a small tolerance (different local optima are legal)
        assert ub <= max(u1 * 2.5, u1 + 2e-5), (seed, u1, ub)


@pytest.mark.parametrize("seed", [601, 602, 603, 604])
@pytest.mark.parametrize("allow_leader", [False, True])
def test_leader_session_parity(seed, allow_leader):
    """The fused rebalance-leaders session (solvers/leader.py) replays the
    host Balance loop move for move: leader redistribution first each
    iteration (total-unbalance gate, heaviest broker's first eligible led
    partition -> lightest broker, swap-on-conflict, steps.go:234-282),
    greedy moves otherwise."""
    rng = random.Random(seed)
    pl = random_partition_list(rng, 16, 5, weighted=True)
    cfg = default_rebalance_config()
    cfg.rebalance_leaders = True
    cfg.allow_leader_rebalancing = allow_leader
    cfg.min_unbalance = 1e-6
    pl_g, pl_s = copy.deepcopy(pl), copy.deepcopy(pl)
    moved_g = greedy_session(pl_g, copy.deepcopy(cfg), 24)
    opl = plan(pl_s, copy.deepcopy(cfg), 24)
    moved_s = [(p.topic, p.partition) for p in (opl.partitions or [])]
    assert moved_s == moved_g
    assert pl_s == pl_g


def test_leader_session_swap_branch():
    """Leadership handed to a broker already in the replica set must swap
    positions in place (replacepl swap branch, utils.go:181-188), not
    duplicate the broker."""
    from test_balancer import P, wrap

    # broker 1 leads everything (heavy); broker 2 follows everywhere
    # (light) -> redistribution must swap leadership in place
    pl = wrap(
        [
            P("t", 0, [1, 2], weight=5.0),
            P("t", 1, [1, 2], weight=1.0),
            P("t", 2, [1, 3], weight=1.0),
        ]
    )
    cfg = default_rebalance_config()
    cfg.rebalance_leaders = True
    cfg.min_unbalance = 1e-9
    pl_g, pl_s = copy.deepcopy(pl), copy.deepcopy(pl)
    greedy_session(pl_g, copy.deepcopy(cfg), 4)
    plan(pl_s, copy.deepcopy(cfg), 4)
    assert pl_s == pl_g
    for p in pl_s.iter_partitions():
        assert len(set(p.replicas)) == len(p.replicas)


def test_churn_bound_config2_shape():
    """Suite-wide churn bound (VERDICT r2 weak #3 / next #6): on the
    suite's config-2 shape (1k partitions / 12 brokers, equal weights,
    rf=2) the batched session must emit within 2% of the batch=1
    reference trajectory's move count at the same final unbalance. The
    supersede post-pass (_superseded_mask) collapses same-(partition,
    slot) re-writes — each emitted entry is real Kafka data movement
    (kafkabalancer.go:177-221)."""
    from kafkabalancer_tpu.utils.synth import synth_cluster

    res = {}
    for batch in (1, 12):
        pl = synth_cluster(1000, 12, rf=2, seed=7, weighted=False)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-6
        opl = plan(pl, cfg, 2000, batch=batch)
        res[batch] = (len(opl), unbalance_of(pl))
    n1, u1 = res[1]
    nb, ub = res[12]
    assert nb <= n1 * 1.02 + 1, res
    assert ub <= u1 * 1.0 + 1e-12, res


def test_superseded_mask_semantics():
    """Only consecutive same-(partition, slot) plain-move runs collapse;
    leadership swaps (SWAP_SLOT) are kept and break runs; interleaved
    different-slot moves on the same partition break runs (the
    intermediate state is observable by the in-between move's replay)."""
    import numpy as np

    from kafkabalancer_tpu.solvers.leader import SWAP_SLOT
    from kafkabalancer_tpu.solvers.scan import _superseded_mask

    # run of three same-slot writes on p0 -> keep only the last
    mp = np.array([0, 0, 0])
    ms = np.array([1, 1, 1])
    assert _superseded_mask(mp, ms).tolist() == [False, False, True]
    # different slot in between breaks the run
    mp = np.array([0, 0, 0])
    ms = np.array([1, 2, 1])
    assert _superseded_mask(mp, ms).tolist() == [True, True, True]
    # swap in between breaks the run and is itself kept
    mp = np.array([0, 0, 0])
    ms = np.array([1, SWAP_SLOT, 1])
    assert _superseded_mask(mp, ms).tolist() == [True, True, True]
    # other partitions never break a run
    mp = np.array([0, 5, 0])
    ms = np.array([1, 1, 1])
    assert _superseded_mask(mp, ms).tolist() == [False, True, True]


def test_leader_session_batched_converges():
    """The batched rebalance-leaders extension (batch > 1: K heaviest
    brokers paired with K lightest, best-gain led partition per pair,
    improving transfers only — solvers/leader.py module docstring) must
    actually CONVERGE below the reference gate (su < min_unbalance,
    steps.go:249-253) where the batch=1 reference trajectory merely
    replays transfers, and every emitted entry must reflect the live
    final assignment."""
    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl = synth_cluster(300, 12, rf=3, seed=7, weighted=True)
    # snapshot BEFORE planning — opl entries alias the live partitions, so
    # the meaningful invariant is that every changed partition is emitted
    before = {
        (p.topic, p.partition): tuple(p.replicas)
        for p in pl.iter_partitions()
    }
    cfg = default_rebalance_config()
    cfg.rebalance_leaders = True
    u0 = unbalance_of(pl)
    opl = plan(pl, cfg, 1 << 14, batch=8)
    uf = unbalance_of(pl)
    assert uf < cfg.min_unbalance, (u0, uf)
    emitted = {(e.topic, e.partition) for e in (opl.partitions or [])}
    changed = {
        (p.topic, p.partition)
        for p in pl.iter_partitions()
        if tuple(p.replicas) != before[(p.topic, p.partition)]
    }
    assert changed and changed <= emitted
    for entry in opl.partitions or []:
        assert len(set(entry.replicas)) == len(entry.replicas)


def test_leader_session_batched_respects_budget():
    """Batched transfer rounds must trim to the remaining budget instead
    of overshooting it (the in-round cumsum cap)."""
    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl = synth_cluster(200, 10, rf=3, seed=11, weighted=True)
    cfg = default_rebalance_config()
    cfg.rebalance_leaders = True
    opl = plan(pl, cfg, 5, batch=8)
    assert len(opl) <= 5


def test_pallas_vmem_gate_falls_back_to_xla():
    """Past the whole-session kernel's scoped-VMEM ceiling, plan() must
    fall back to the XLA session instead of OOMing Mosaic compilation.
    On CPU this is observable directly: engine='pallas' normally fails
    without a TPU backend, but above the gate the fallback engages first
    and the plan succeeds. The restricted mode (an explicit per-partition
    broker list keeps the [P, B] allowed matrix resident) has the lower
    ceiling, so a 17k x 200 instance with one restricted partition trips
    it."""
    from kafkabalancer_tpu.solvers.scan import (
        PALLAS_VMEM_CELLS_RESTRICTED,
    )
    from kafkabalancer_tpu.utils.synth import synth_cluster

    n_parts = 17_000  # buckets to 32768 x 512 cells
    assert 32768 * 512 > PALLAS_VMEM_CELLS_RESTRICTED
    pl = synth_cluster(n_parts, 300, rf=2, seed=3, weighted=True)
    p0 = pl.partitions[0]
    p0.brokers = sorted(set(p0.replicas) | {1, 2})
    cfg = default_rebalance_config()
    cfg.min_unbalance = 0.0
    opl = plan(pl, cfg, 3, batch=8, engine="pallas")
    assert len(opl) == 3


def test_pallas_gate_derives_from_device_not_literals(monkeypatch):
    """r4 verdict #7: the VMEM gate is a device-derived verdict ladder,
    not the one-chip literals. With the literals effectively DELETED
    (zeroed), a cached per-device verdict still routes the kernel; a
    cached negative verdict overrides even huge literals; and a VMEM
    OOM at dispatch records a lasting negative verdict and falls back
    to XLA within the same plan() call."""
    import kafkabalancer_tpu.solvers.scan as scan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    monkeypatch.setattr(scan, "_gate_cache_path", lambda: None)
    monkeypatch.setattr(scan, "_gate_mem", {})

    def fresh():
        pl = synth_cluster(60, 8, rf=2, seed=5, weighted=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 0.0
        return pl, cfg

    from kafkabalancer_tpu.ops import tensorize as tz
    from kafkabalancer_tpu.solvers.pallas_session import TILE_P

    pl0, cfg0 = fresh()
    dp = tz(pl0, cfg0, min_bucket=TILE_P)
    P, R = dp.replicas.shape
    B = dp.bvalid.shape[0]
    # plan(pl, cfg, 3, ...) dispatches chunk=3 -> max_moves bucket 128;
    # the gate key carries it (a verdict at one move-log size must not
    # admit or ban another, ADVICE r5)
    key = scan._gate_key(P, B, R, True, False, 128)

    # literals deleted + positive cached verdict: the kernel is routed
    # (observable on CPU as the pallas BalanceError instead of fallback)
    monkeypatch.setattr(scan, "PALLAS_VMEM_CELLS", 0)
    monkeypatch.setattr(scan, "PALLAS_VMEM_CELLS_RESTRICTED", 0)
    scan._gate_mem[key] = True
    pl, cfg = fresh()
    with pytest.raises(scan.BalanceError, match="pallas engine failed"):
        scan.plan(pl, cfg, 3, batch=8, engine="pallas")

    # negative cached verdict overrides even infinite literals
    monkeypatch.setattr(scan, "PALLAS_VMEM_CELLS", 1 << 60)
    monkeypatch.setattr(scan, "PALLAS_VMEM_CELLS_RESTRICTED", 1 << 60)
    scan._gate_mem[key] = False
    pl, cfg = fresh()
    opl = scan.plan(pl, cfg, 3, batch=8, engine="pallas")
    assert len(opl) == 3  # fell back to the XLA session cleanly

    # a SCOPED-VMEM OOM at dispatch: lasting verdict recorded, SAME call
    # falls back (the narrow Mosaic/vmem signature is deterministic —
    # the kernel's budget, not device weather)
    scan._gate_mem.clear()
    real_dispatch = scan._dispatch_chunk
    oomed = []

    def oom_once(dp_, cfg_, chunk, dtype, batch, engine, **kw):
        if engine == "pallas" and not oomed:
            oomed.append(True)
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Ran out of memory in scoped vmem"
            )
        return real_dispatch(dp_, cfg_, chunk, dtype, batch, engine, **kw)

    monkeypatch.setattr(scan, "_dispatch_chunk", oom_once)
    pl, cfg = fresh()
    opl = scan.plan(pl, cfg, 3, batch=8, engine="pallas")
    assert len(opl) == 3
    assert oomed  # the kernel path was attempted first
    assert scan._gate_mem.get(key) is False  # lasting verdict recorded


def test_dispatch_hbm_oom_is_one_shot_fallback(monkeypatch):
    """ADVICE r5: a BROAD dispatch-time OOM (transient HBM exhaustion,
    device contention — no scoped-VMEM/Mosaic signature) falls back to
    the XLA session for the chunk but records NO lasting verdict, so the
    next plan() retries the kernel instead of being permanently banned."""
    import kafkabalancer_tpu.solvers.scan as scan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    monkeypatch.setattr(scan, "_gate_cache_path", lambda: None)
    monkeypatch.setattr(scan, "_gate_mem", {})
    # huge literals: the prior admits, no compile probe runs
    monkeypatch.setattr(scan, "PALLAS_VMEM_CELLS", 1 << 60)
    monkeypatch.setattr(scan, "PALLAS_VMEM_CELLS_RESTRICTED", 1 << 60)

    real_dispatch = scan._dispatch_chunk
    attempts = []

    def oom_hbm(dp_, cfg_, chunk, dtype, batch, engine, **kw):
        if engine == "pallas":
            attempts.append(True)
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory allocating 1234 "
                "bytes in HBM"
            )
        return real_dispatch(dp_, cfg_, chunk, dtype, batch, engine, **kw)

    monkeypatch.setattr(scan, "_dispatch_chunk", oom_hbm)

    def fresh():
        pl = synth_cluster(60, 8, rf=2, seed=5, weighted=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 0.0
        return pl, cfg

    pl, cfg = fresh()
    opl = scan.plan(pl, cfg, 3, batch=8, engine="pallas")
    assert len(opl) == 3  # fell back to XLA within the same call
    assert len(attempts) == 1
    assert scan._gate_mem == {}  # NO lasting ban
    # a second plan() attempts the kernel again (one-shot semantics)
    pl, cfg = fresh()
    opl = scan.plan(pl, cfg, 3, batch=8, engine="pallas")
    assert len(opl) == 3
    assert len(attempts) == 2


def test_probe_persists_only_scoped_vmem_verdicts(monkeypatch):
    """The compile probe persists a negative verdict only for the
    scoped-VMEM/Mosaic signatures; an unrelated (or broad-OOM) probe
    failure rejects for this call WITHOUT a cached ban."""
    import kafkabalancer_tpu.solvers.scan as scan

    monkeypatch.setattr(scan, "_gate_cache_path", lambda: None)
    monkeypatch.setattr(scan, "_gate_mem", {})
    # zero literals force the probe; a fake TPU device gets past the
    # no-hardware early-out (the probe itself is stubbed below)
    monkeypatch.setattr(scan, "PALLAS_VMEM_CELLS", 0)
    monkeypatch.setattr(scan, "PALLAS_VMEM_CELLS_RESTRICTED", 0)

    class _FakeDev:
        platform = "tpu"
        device_kind = "fake-tpu"

    monkeypatch.setattr(scan.jax, "devices", lambda *a, **kw: [_FakeDev()])

    from kafkabalancer_tpu.models import default_rebalance_config
    from kafkabalancer_tpu.ops.tensorize import tensorize
    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl = synth_cluster(60, 8, rf=2, seed=5, weighted=True)
    cfg = default_rebalance_config()
    scan._settle_head(pl, cfg, 0)
    dp = tensorize(pl, cfg)

    import kafkabalancer_tpu.solvers.pallas_session as ps

    calls = []

    def boom(*a, **kw):
        raise RuntimeError(calls[-1])

    monkeypatch.setattr(ps, "pallas_session", boom)

    # broad OOM text without vmem/mosaic: rejected, nothing cached
    calls.append("RESOURCE_EXHAUSTED: out of memory in HBM")
    assert scan.pallas_session_fits(dp, None, True, False, 128) is False
    assert scan._gate_mem == {}

    # scoped-VMEM signature: rejected AND cached
    calls.append("Mosaic failed: scoped vmem limit exceeded")
    assert scan.pallas_session_fits(dp, None, True, False, 128) is False
    P, R = dp.replicas.shape
    B = dp.bvalid.shape[0]
    assert scan._gate_mem.get(scan._gate_key(P, B, R, True, False, 128)) is False


@pytest.mark.parametrize("polish", [False, True])
def test_plan_chunk_reentry_equivalent_quality(polish):
    """Sessions that exhaust a device chunk re-enter with the mutated
    assignment (re-tensorize + fresh dispatch). Chunking is not
    bit-stable — a fresh chunk recomputes loads from scratch while a
    running session updates them incrementally, so near-ties can resolve
    differently (the documented fused-session caveat) and batch>1 chunk
    boundaries truncate an iteration's disjoint commit set. What IS
    promised: a valid final assignment of equivalent quality, with every
    emitted entry reflecting the live partition's final state."""
    from kafkabalancer_tpu.utils.synth import synth_cluster

    us = {}
    for chunk in (4, 8192):
        pl = synth_cluster(60, 8, rf=2, seed=5, weighted=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-9
        opl = plan(pl, cfg, 40, batch=4, chunk_moves=chunk, polish=polish)
        live = {
            (p.topic, p.partition): tuple(p.replicas)
            for p in pl.iter_partitions()
        }
        for entry in opl.partitions or []:
            assert tuple(entry.replicas) == live[(entry.topic, entry.partition)]
            assert len(set(entry.replicas)) == len(entry.replicas)
        us[chunk] = unbalance_of(pl)
    assert us[4] <= us[8192] * 2 + 1e-9 and us[8192] <= us[4] * 2 + 1e-9


def test_leader_plan_chunk_reentry():
    from kafkabalancer_tpu.utils.synth import synth_cluster

    res = {}
    for chunk in (2, 8192):
        pl = synth_cluster(40, 6, rf=2, seed=9, weighted=True)
        cfg = default_rebalance_config()
        cfg.rebalance_leaders = True
        opl = plan(pl, cfg, 10, chunk_moves=chunk)
        res[chunk] = (
            len(opl),
            [(p.topic, p.partition, tuple(p.replicas)) for p in pl.iter_partitions()],
        )
    assert res[2] == res[8192]


def _pen(load, avg):
    rel = load / avg - 1.0
    return rel * rel * (1.0 if rel > 0 else 0.5)


def test_prefix_accept_sequential_exactness():
    """Direct invariant test for the shared acceptance core: replaying
    the accepted moves ONE AT A TIME in log order must (a) strictly
    improve the objective at every step by more than min_unbalance,
    (b) end with exactly the loads the batch application computes, and
    (c) always accept the rank-0 candidate when it improves. Candidates
    deliberately share sources and targets so the per-broker net prefix
    sums are load-bearing."""
    import jax.numpy as jnp
    import numpy as np

    from kafkabalancer_tpu.solvers.scan import prefix_accept

    rng = random.Random(4242)
    B, K = 8, 24
    min_unb = 1e-9
    for trial in range(20):
        loads = np.array([rng.uniform(1.0, 10.0) for _ in range(B)])
        avg = loads.sum() / B
        su = sum(_pen(x, avg) for x in loads)
        p = np.array([rng.randrange(1000) for _ in range(K)], np.int32)
        s_ = np.array([rng.randrange(B) for _ in range(K)], np.int32)
        t = np.array(
            [(s + 1 + rng.randrange(B - 1)) % B for s in s_], np.int32
        )
        w = np.array([rng.uniform(0.01, 2.0) for _ in range(K)])
        # plain deltas as the scorers produce them (A + C form)
        vals = np.array(
            [
                su
                + (_pen(loads[s_[k]] - w[k], avg) - _pen(loads[s_[k]], avg))
                + (_pen(loads[t[k]] + w[k], avg) - _pen(loads[t[k]], avg))
                for k in range(K)
            ]
        )
        ok, pos, cnt = prefix_accept(
            jnp.asarray(vals), jnp.asarray(p), jnp.asarray(s_),
            jnp.asarray(t), jnp.asarray(w), jnp.asarray(loads),
            jnp.asarray(avg), jnp.asarray(su), jnp.asarray(min_unb),
            jnp.asarray(1e9), jnp.int32(0), jnp.int32(K), jnp.int32(K),
            K,
        )
        ok = np.asarray(ok)
        pos = np.asarray(pos)
        # (c) the global best candidate is accepted iff it improves
        best = int(np.argmin(vals))
        if vals[best] < su - min_unb:
            assert ok[best], (trial, vals, ok)
        else:
            assert int(cnt) == 0
        # accepted partitions are unique
        acc = np.nonzero(ok)[0]
        assert len({int(p[k]) for k in acc}) == len(acc)
        # (a) + (b): sequential replay in log order
        L = loads.copy()
        prev = su
        for k in sorted(acc, key=lambda k: pos[k]):
            L[s_[k]] -= w[k]
            L[t[k]] += w[k]
            cur = sum(_pen(x, avg) for x in L)
            assert cur < prev - min_unb, (trial, k, prev, cur)
            prev = cur
        batch_L = loads.copy()
        np.add.at(batch_L, s_[acc], -w[acc])
        np.add.at(batch_L, t[acc], w[acc])
        assert np.allclose(L, batch_L, rtol=0, atol=1e-12)


def test_paired_best_brute_force():
    """paired_best's winners checked against a brute-force scan: for
    every live pair, the reported candidate is feasible (holds a replica
    on the hot broker, target allowed and not a member) and achieves the
    minimum A+C over all partitions."""
    import jax.numpy as jnp
    import numpy as np

    from kafkabalancer_tpu.ops import cost, tensorize
    from kafkabalancer_tpu.solvers.scan import _settle_head

    rng = random.Random(77)
    pl = random_partition_list(rng, 60, 9, weighted=True, with_consumers=True)
    cfg = default_rebalance_config()
    cfg.allow_leader_rebalancing = True
    _settle_head(pl, cfg, 10)
    dp = tensorize(pl, cfg)
    P, R = dp.replicas.shape
    B = dp.bvalid.shape[0]
    w = jnp.asarray(dp.weights)
    nc = jnp.asarray(dp.ncons, w.dtype)
    loads = cost.broker_loads(
        jnp.asarray(dp.replicas), w, jnp.asarray(dp.nrep_cur), nc, B
    )
    bvalid = jnp.asarray(dp.bvalid)
    vals, p, slot, s_i, t_i, live = cost.paired_best(
        loads, jnp.asarray(dp.replicas), jnp.asarray(dp.allowed),
        jnp.asarray(dp.member), bvalid, w, jnp.asarray(dp.nrep_cur),
        jnp.asarray(dp.nrep_tgt), nc, jnp.asarray(dp.pvalid),
        jnp.int32(cfg.min_replicas_for_rebalancing),
        allow_leader=True,
    )
    vals, p, slot = np.asarray(vals), np.asarray(p), np.asarray(slot)
    s_i, t_i, live = np.asarray(s_i), np.asarray(t_i), np.asarray(live)
    loads_np = np.asarray(loads)
    nb = int(np.asarray(bvalid).sum())
    avg = float(np.where(np.asarray(bvalid), loads_np, 0.0).sum()) / nb
    F = np.where(
        np.asarray(bvalid),
        np.asarray([_pen(x, avg) for x in loads_np]),
        0.0,
    )
    su = float(F.sum())

    member = np.asarray(dp.member)
    allowed = np.asarray(dp.allowed)
    reps = np.asarray(dp.replicas)
    ncur = np.asarray(dp.nrep_cur)
    ntgt = np.asarray(dp.nrep_tgt)
    ncons = np.asarray(dp.ncons)
    pvalid = np.asarray(dp.pvalid)
    weights = np.asarray(dp.weights)
    minrep = cfg.min_replicas_for_rebalancing

    order = sorted(range(B), key=lambda b: (loads_np[b] if bvalid[b] else np.inf, b))
    checked = 0
    for i in range(len(vals)):
        if not live[i]:
            assert vals[i] == np.inf
            continue
        assert order[nb - 1 - i] == s_i[i] and order[i] == t_i[i]
        # brute-force best over all (partition, slot is implied by s_i)
        best = np.inf
        for q in range(P):
            if not pvalid[q] or ntgt[q] < minrep:
                continue
            if not (allowed[q, t_i[i]] and not member[q, t_i[i]] and bvalid[t_i[i]]):
                continue
            # follower: s_i in a follower slot
            for r in range(1, ncur[q]):
                if reps[q, r] == s_i[i]:
                    d = (
                        _pen(loads_np[s_i[i]] - weights[q], avg) - F[s_i[i]]
                        + _pen(loads_np[t_i[i]] + weights[q], avg) - F[t_i[i]]
                    )
                    best = min(best, d)
            # leader with true premium
            if ncur[q] >= 1 and reps[q, 0] == s_i[i]:
                wl = weights[q] * (ncur[q] + ncons[q])
                d = (
                    _pen(loads_np[s_i[i]] - wl, avg) - F[s_i[i]]
                    + _pen(loads_np[t_i[i]] + wl, avg) - F[t_i[i]]
                )
                best = min(best, d)
        if best == np.inf:
            assert vals[i] == np.inf
            continue
        assert vals[i] - su == pytest.approx(best, rel=1e-9, abs=1e-12)
        # the reported (p, slot) realizes the value
        q, r = int(p[i]), int(slot[i])
        assert reps[q, r] == s_i[i]
        checked += 1
    assert checked > 0


def _colo_count(pl):
    import collections

    c = collections.Counter()
    for p in pl.iter_partitions():
        for b in p.replicas:
            c[(p.topic, b)] += 1
    return sum(v - 1 for v in c.values() if v > 1)


def test_colocation_session_reaches_floor():
    """The colocation-aware batched session must drive same-topic
    colocations to the pigeonhole floor sum(max(0, 3*size - B)) on a
    zipf-topic instance while converging the load objective, and every
    emitted assignment must stay duplicate-free. Quality cross-check:
    the greedy combined-objective session matches the beam solver's
    result on this instance class (solvers/beam.py searches the same
    objective with lookahead)."""
    import collections

    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl0 = synth_cluster(600, 16, rf=3, seed=5, weighted=True, zipf_topics=True)
    sizes = collections.Counter(p.topic for p in pl0.iter_partitions())
    floor = sum(max(0, 3 * s - 16) for s in sizes.values())
    start = _colo_count(pl0)
    assert start > floor

    cfg = default_rebalance_config()
    cfg.allow_leader_rebalancing = True
    cfg.min_unbalance = 1e-9
    pl = copy.deepcopy(pl0)
    u0 = unbalance_of(pl)
    opl = plan(pl, cfg, 100000, batch=16, anti_colocation=0.001)
    assert len(opl) > 0
    assert _colo_count(pl) == floor
    assert unbalance_of(pl) < u0 * 1e-4
    for p in pl.iter_partitions():
        assert len(set(p.replicas)) == len(p.replicas)


def test_colocation_session_objective_decreases_per_chunk():
    """Chunked re-entry of the colocation session: the combined objective
    u + lam*colo is monotone across chunk boundaries (each chunk's
    accepted moves improve it by their exact deltas)."""
    from kafkabalancer_tpu.utils.synth import synth_cluster

    lam = 0.01
    pl = synth_cluster(300, 10, rf=3, seed=11, weighted=True, zipf_topics=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-9
    prev = unbalance_of(pl) + lam * _colo_count(pl)
    moved = 0
    for _ in range(20):
        opl = plan(pl, cfg, 8, batch=8, anti_colocation=lam)
        cur = unbalance_of(pl) + lam * _colo_count(pl)
        if len(opl) == 0:
            break
        moved += len(opl)
        assert cur < prev
        prev = cur
    assert moved > 0


def test_colocation_session_validation():
    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl = synth_cluster(40, 6, rf=2, seed=1, weighted=True)
    cfg = default_rebalance_config()
    with pytest.raises(ValueError, match="batch"):
        plan(pl, cfg, 10, batch=1, anti_colocation=0.1)
    cfg_rl = default_rebalance_config()
    cfg_rl.rebalance_leaders = True
    with pytest.raises(ValueError, match="rebalance_leaders"):
        plan(pl, cfg_rl, 10, batch=8, anti_colocation=0.1)
    # an EXPLICIT pallas engine request with anti_colocation is overridden
    # to the XLA colocation session — with a warning API callers can see
    with pytest.warns(UserWarning, match="overridden"):
        plan(
            copy.deepcopy(pl), default_rebalance_config(), 4, batch=8,
            anti_colocation=0.1, engine="pallas-interpret",
        )


def test_colocation_with_polish_reaches_floor_and_polish_grade_load():
    """anti_colocation now COMPOSES with polish: the combined-objective
    alternation must still land the colocation count on the pigeonhole
    floor (the swap phases score the ±λ pair terms, so they cannot undo
    it) while driving the load objective strictly below what the
    colocation session alone reaches (the polish-grade floor the VERDICT
    r4 gap called out)."""
    import collections

    from kafkabalancer_tpu.utils.synth import synth_cluster

    lam = 0.001
    B = 16
    cfg = default_rebalance_config()
    cfg.allow_leader_rebalancing = True
    cfg.min_unbalance = 1e-9

    pl_plain = synth_cluster(600, B, rf=3, seed=5, weighted=True,
                             zipf_topics=True)
    sizes = collections.Counter(p.topic for p in pl_plain.iter_partitions())
    floor = sum(max(0, 3 * s - B) for s in sizes.values())
    plan(pl_plain, copy.deepcopy(cfg), 100000, batch=16,
         anti_colocation=lam)
    u_plain = unbalance_of(pl_plain)
    assert _colo_count(pl_plain) == floor

    pl_pol = synth_cluster(600, B, rf=3, seed=5, weighted=True,
                           zipf_topics=True)
    plan(pl_pol, copy.deepcopy(cfg), 100000, batch=16,
         anti_colocation=lam, polish=True)
    u_pol = unbalance_of(pl_pol)
    assert _colo_count(pl_pol) == floor
    # polish-grade load floor: strictly better than the move-only
    # colocation session, by orders of magnitude on this instance class
    assert u_pol < u_plain
    assert u_pol < u_plain * 1e-2
    for p in pl_pol.iter_partitions():
        assert len(set(p.replicas)) == len(p.replicas)


def test_colocation_session_restricted_brokers():
    """The colocation session honors per-partition broker restrictions:
    every emitted assignment stays inside the partition's allowed set
    while the combined objective still improves (with consumers, so the
    leader premium rides the true applied delta)."""
    rng = random.Random(909)
    pl = random_partition_list(
        rng, 80, 10, weighted=True, with_consumers=True,
        restrict_brokers=True,
    )
    cfg = default_rebalance_config()
    cfg.allow_leader_rebalancing = True
    cfg.min_unbalance = 1e-9
    lam = 0.01
    allowed = {
        (p.topic, p.partition): set(p.brokers or [])
        for p in pl.iter_partitions()
        if p.brokers
    }
    u0 = unbalance_of(pl) + lam * _colo_count(pl)
    opl = plan(pl, cfg, 100000, batch=8, anti_colocation=lam)
    u1 = unbalance_of(pl) + lam * _colo_count(pl)
    assert u1 <= u0
    for p in pl.iter_partitions():
        key = (p.topic, p.partition)
        if key in allowed and allowed[key]:
            assert set(p.replicas).issubset(allowed[key]), (key, p.replicas)
        assert len(set(p.replicas)) == len(p.replicas)
    assert len(opl) >= 0


def test_colocation_session_leader_gated_optimum_certificate():
    """Without -allow-leader the colocation session must stop at a TRUE
    follower-move local optimum of the combined objective: the suite's
    exhaustive vectorized certificate (benchmarks/suite.py
    best_follower_delta) reports a non-improving best delta at the
    converged state."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_suite",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks", "suite.py",
        ),
    )
    suite = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(suite)

    from kafkabalancer_tpu.utils.synth import synth_cluster

    lam = 0.001
    pl = synth_cluster(800, 20, rf=3, seed=21, weighted=True, zipf_topics=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-9
    plan(pl, cfg, 100000, batch=16, anti_colocation=lam)
    bfd = suite.best_follower_delta(pl, lam)
    assert bfd > -cfg.min_unbalance, bfd
