"""Persistent planning daemon (kafkabalancer_tpu/serve/): lifecycle,
fallback parity, coalescing, the incremental tensorize cache, and the
no-jax client pin.

The load-bearing pins:

- with the daemon STOPPED, a forwarding-enabled invocation is
  byte-identical (stdout + exit code, stderr modulo timestamps) to
  ``-no-daemon`` — the outer automation loop must not be able to tell
  the feature exists until a daemon is started;
- a SERVED plan is byte-identical to the in-process plan;
- the client path of a served invocation never imports jax (that is the
  entire point of the daemon);
- two concurrent same-bucket requests coalesce into one dispatch window
  and still each get their own correct plan.
"""

import io
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from kafkabalancer_tpu import cli
from kafkabalancer_tpu.serve import client as sclient
from kafkabalancer_tpu.serve import protocol
from kafkabalancer_tpu.serve.daemon import Coalescer, Daemon, PlanRequest

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")

# Go-log timestamp prefix on stderr lines ("2025/01/01 00:00:00 ")
_TS = re.compile(r"^\d{4}/\d{2}/\d{2} \d{2}:\d{2}:\d{2} ", re.M)


def run_cli(args, stdin=""):
    out, err = io.StringIO(), io.StringIO()
    rv = cli.run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


@pytest.fixture
def sock_dir():
    # NOT tmp_path: unix socket paths are limited to ~104 bytes and
    # pytest's tmp_path nests deep enough to cross it
    d = tempfile.mkdtemp(prefix="kbs-")
    yield d
    shutil.rmtree(d, ignore_errors=True)


@pytest.fixture
def daemon(sock_dir):
    """A live daemon on a private socket, serving from a background
    thread in THIS process (warm=False: lifecycle tests need no
    backend). Always shut down, even on test failure."""
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(sock, idle_timeout=60.0, warm=False, log=lambda _m: None)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    yield sock, d
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0], rc_box


# --- protocol -------------------------------------------------------------


def test_frame_roundtrip_and_limits():
    import socket as socket_mod

    a, b = socket_mod.socketpair()
    try:
        msg = {"v": 1, "op": "hello", "blob": "x" * 10000}
        protocol.write_frame(a, msg)
        assert protocol.read_frame(b) == msg
        # clean EOF at a frame boundary reads as None
        a.close()
        assert protocol.read_frame(b) is None
    finally:
        b.close()
    with pytest.raises(ValueError):
        protocol.write_frame(None, {"x": "y" * (protocol.MAX_FRAME_BYTES + 1)})


def test_resolve_socket_path_precedence(monkeypatch):
    monkeypatch.setenv("KAFKABALANCER_TPU_SOCKET", "/env/path.sock")
    assert protocol.resolve_socket_path("") == "/env/path.sock"
    assert protocol.resolve_socket_path("/flag.sock") == "/flag.sock"
    monkeypatch.delenv("KAFKABALANCER_TPU_SOCKET")
    assert protocol.resolve_socket_path("").endswith(".sock")


# --- lifecycle ------------------------------------------------------------


def test_handshake_pidfile_and_clean_shutdown(sock_dir):
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(sock, idle_timeout=60.0, warm=False, log=lambda _m: None)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    hello = None
    while time.monotonic() < deadline and hello is None:
        hello = sclient.daemon_alive(sock)
        time.sleep(0.02)
    assert hello is not None
    assert hello["pid"] == os.getpid()
    assert hello["requests"] == 0
    with open(protocol.pidfile_path(sock)) as f:
        assert int(f.read().strip()) == os.getpid()
    assert sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0]
    assert not os.path.exists(sock)
    assert not os.path.exists(protocol.pidfile_path(sock))


def test_idle_timeout_shuts_down(sock_dir):
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(sock, idle_timeout=0.6, warm=False, log=lambda _m: None)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    t.join(20)
    assert not t.is_alive(), "idle timeout never fired"
    assert rc_box == [0]
    assert not os.path.exists(sock)


def test_stale_socket_is_not_alive_and_gets_replaced(sock_dir):
    """A socket file with no listener behind it: the client treats it as
    daemon-down (fallback), and a starting daemon unlinks it."""
    import socket as socket_mod

    sock = os.path.join(sock_dir, "kb.sock")
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.bind(sock)
    s.close()  # leaves the file behind, nobody listening
    assert os.path.exists(sock)
    assert sclient.daemon_alive(sock) is None
    assert sclient.forward_plan(sock, ["-no-daemon=true"], "") is None
    d = Daemon(sock, idle_timeout=60.0, warm=False, log=lambda _m: None)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon did not replace the stale socket")
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0]


def test_second_daemon_refuses_live_socket(daemon):
    sock, _d = daemon
    d2 = Daemon(sock, idle_timeout=60.0, warm=False, log=lambda _m: None)
    assert d2.serve_forever() == 3
    # the loser must not have torn down the winner's socket
    assert sclient.daemon_alive(sock) is not None


def test_serve_flag_rejects_input_flags():
    rv, _out, err = run_cli(["-serve", f"-input={FIXTURE}"])
    assert rv == 3
    assert "-serve takes no input" in err


# --- served-vs-inprocess parity ------------------------------------------


def test_served_plan_byte_identical_to_inprocess(daemon):
    sock, d = daemon
    rv_s, out_s, _err_s = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
    )
    rv_l, out_l, _err_l = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-no-daemon"]
    )
    assert rv_s == rv_l == 0
    assert out_s == out_l
    assert d._requests == 1  # it really went through the daemon


def test_served_stdin_plan_byte_identical(daemon):
    sock, _d = daemon
    with open(FIXTURE) as fh:
        src = fh.read()
    rv_s, out_s, _ = run_cli(
        ["-input-json", f"-serve-socket={sock}"], stdin=src
    )
    rv_l, out_l, _ = run_cli(["-input-json", "-no-daemon"], stdin=src)
    assert rv_s == rv_l == 0
    assert out_s == out_l


def test_served_error_exit_codes_match(daemon):
    """Exit codes 1/2/3 round-trip the daemon unchanged."""
    sock, _d = daemon
    cases = [
        (["-input-json"], "::malformed::", 2),
        (["-input-json", f"-input={FIXTURE}", "-broker-ids=bogus"], "", 3),
        (["-input-json", "-input=/nonexistent/x.json"], "", 1),
    ]
    for args, stdin, want in cases:
        rv_s, out_s, _ = run_cli(args + [f"-serve-socket={sock}"], stdin)
        rv_l, out_l, _ = run_cli(args + ["-no-daemon"], stdin)
        assert rv_s == rv_l == want, (args, rv_s, rv_l)
        assert out_s == out_l


def test_served_metrics_carry_attribution(daemon, sock_dir):
    sock, _d = daemon
    mpath = os.path.join(sock_dir, "m.json")
    rv, _out, _err = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}",
         f"-metrics-json={mpath}"]
    )
    assert rv == 0
    with open(mpath) as f:
        payload = json.load(f)
    g = payload["gauges"]
    assert g["served"] is True
    assert g["serve.requests"] >= 1.0
    assert "serve.coalesced" in g and "serve.cache_hits" in g
    # exactly ONE metrics line and it came from the daemon side: the
    # client's own exporter must not double-write
    with open(mpath) as f:
        assert len(f.read().strip().splitlines()) == 1


def test_served_relative_input_error_stderr_parity(daemon, monkeypatch):
    """Exit-1 on a RELATIVE -input path that does not exist: with a live
    daemon the stderr must still name the path exactly as the user
    spelled it (review r4: forwarding the flag absolutized it, so the
    served error named /abs/missing.json while the stateless one named
    missing.json)."""
    sock, _d = daemon
    monkeypatch.chdir(tempfile.mkdtemp(prefix="kbs-rel-"))
    args = ["-input-json", "-input=does-not-exist.json"]
    rv_s, out_s, err_s = run_cli(args + [f"-serve-socket={sock}"])
    rv_n, out_n, err_n = run_cli(args + ["-no-daemon"])
    assert rv_s == rv_n == 1
    assert out_s == out_n
    assert _TS.sub("", err_s) == _TS.sub("", err_n)
    assert "does-not-exist.json" in err_s


def test_served_relative_input_file_plans_through_daemon(daemon):
    """A READABLE relative -input forwards (inlined as request stdin)
    and plans byte-identically to the stateless path."""
    sock, d = daemon
    rel = os.path.relpath(FIXTURE)
    rv_s, out_s, _ = run_cli(
        ["-input-json", f"-input={rel}", f"-serve-socket={sock}"]
    )
    rv_n, out_n, _ = run_cli(["-input-json", f"-input={rel}", "-no-daemon"])
    assert rv_s == rv_n == 0
    assert out_s == out_n
    assert d._requests >= 1  # genuinely served, not a silent fallback


def test_process_warm_latch_suppresses_per_request_warm_thread(
    sock_dir, monkeypatch
):
    """Once a serving process is marked durably warm (daemon startup-warm
    hook), planning invocations in it skip the per-request warm-thread
    launch — the one-time costs it overlaps are already paid. A process
    that never marked itself warm still launches it."""
    from kafkabalancer_tpu.ops import coldstart

    monkeypatch.setattr(coldstart, "_process_warm", threading.Event())

    def spans_of(tag):
        mpath = os.path.join(sock_dir, f"warmlatch-{tag}.json")
        rv, _out, err = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-solver=tpu",
             "-no-daemon", f"-metrics-json={mpath}"]
        )
        assert rv == 0, err
        with open(mpath) as f:
            return {s["name"] for s in json.load(f)["spans"]}

    assert "warm_thread_launch" in spans_of("cold")
    coldstart.mark_process_warm()
    assert "warm_thread_launch" not in spans_of("warm")


# --- daemon-down fallback parity -----------------------------------------


def test_daemon_down_fallback_byte_identical(sock_dir):
    """The tentpole's contract pin: with no daemon reachable, the
    forwarding-enabled invocation is byte-identical (stdout + rc,
    stderr modulo log timestamps) to an explicit -no-daemon one, for
    exit codes 0 through 3."""
    sock = os.path.join(sock_dir, "absent.sock")
    assert not os.path.exists(sock)
    with open(FIXTURE) as fh:
        src = fh.read()
    cases = [
        (["-input-json", f"-input={FIXTURE}"], "", 0),
        (["-input-json"], src, 0),  # stdin read + replay path
        (["-input-json"], "::malformed::", 2),
        (["-input-json", f"-input={FIXTURE}", "-broker-ids=x"], "", 3),
        (["-input-json", "-input=/nonexistent/x.json"], "", 1),
    ]
    for args, stdin, want in cases:
        rv_f, out_f, err_f = run_cli(
            args + [f"-serve-socket={sock}"], stdin
        )
        rv_n, out_n, err_n = run_cli(args + ["-no-daemon"], stdin)
        assert rv_f == rv_n == want, (args, rv_f, rv_n)
        assert out_f == out_n
        assert _TS.sub("", err_f) == _TS.sub("", err_n)


def test_profiling_flags_never_forward(daemon, sock_dir, monkeypatch):
    """-pprof / -jax-profile pin the work to THIS process by intent."""
    sock, d = daemon
    pprof_path = os.path.join(sock_dir, "cpu.pprof")
    rv, _out, _err = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}",
         "-pprof", f"-pprof-path={pprof_path}"]
    )
    assert rv == 0
    assert d._requests == 0  # never reached the daemon
    assert os.path.exists(pprof_path)


# --- canonical forwarded argv --------------------------------------------


def test_forward_argv_canonicalization(monkeypatch, sock_dir):
    """The forwarded argv: -no-daemon pinned, serve/profiling flags
    stripped, non-default flags as -name=value, paths absolutized."""
    captured = {}

    def fake_forward(sock, argv, stdin_text, **kw):
        captured["argv"] = argv
        captured["stdin"] = stdin_text
        return sclient.ServedResult(rc=0, stdout="", stderr="")

    monkeypatch.setattr(sclient, "forward_plan", fake_forward)
    monkeypatch.setattr(sclient, "socket_exists", lambda _p: True)
    sock = os.path.join(sock_dir, "any.sock")
    rel_metrics = "rel/metrics.json"
    rv, _out, _err = run_cli(
        ["-input-json", "-input", FIXTURE, "-max-reassign=3",
         "-fused", "-fused-batch=4", f"-serve-socket={sock}",
         f"-metrics-json={rel_metrics}"]
    )
    assert rv == 0
    argv = captured["argv"]
    assert argv[0] == "-no-daemon=true"
    # -input is inlined as request stdin, never forwarded as a flag:
    # the daemon needs no filesystem access and open-failure stderr
    # keeps naming the path as the user spelled it
    assert not any(a.startswith("-input=") for a in argv)
    assert captured["stdin"] == open(FIXTURE).read()
    assert "-max-reassign=3" in argv
    assert "-fused=true" in argv
    assert "-fused-batch=4" in argv
    assert f"-metrics-json={os.path.abspath(rel_metrics)}" in argv
    assert not any(a.startswith("-serve") for a in argv)
    # defaults are omitted: the daemon's own defaults are identical
    assert not any(a.startswith("-beam-width") for a in argv)


# --- coalescing -----------------------------------------------------------


def test_coalescer_groups_same_bucket():
    """Two same-bucket requests queued behind a blocker drain as ONE
    dispatch window (second flagged coalesced); a different-bucket
    request does not ride along."""
    release = threading.Event()
    entered = threading.Event()
    handled = []

    def handle(req, coalesced):
        if req.argv == ["block"]:
            entered.set()
            release.wait(10)
        handled.append((req.argv[0], coalesced))
        req.response = {"ok": True, "id": req.argv[0]}

    buckets = {"block": (1, 1, 1, True), "a1": (8, 2, 4, True),
               "a2": (8, 2, 4, True), "b": (16, 2, 4, False)}
    co = Coalescer(handle, lambda r: buckets[r.argv[0]])
    results = {}

    def submit(name):
        results[name] = co.submit(PlanRequest([name], None))

    threads = [threading.Thread(target=submit, args=("block",))]
    threads[0].start()
    assert entered.wait(10), "worker never picked up the blocker"
    for name in ("a1", "a2", "b"):
        threads.append(threading.Thread(target=submit, args=(name,)))
        threads[-1].start()
    # wait until all three are queued behind the blocker
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(co._dq) < 3:
        time.sleep(0.01)
    assert len(co._dq) == 3, "followers never queued"
    release.set()
    for t in threads:
        t.join(10)
    co.stop()
    assert {r["id"] for r in results.values()} == {"block", "a1", "a2", "b"}
    flags = dict(handled)
    assert flags["block"] is False
    # exactly one of the same-bucket pair rode the other's window
    assert [flags["a1"], flags["a2"]].count(True) == 1
    assert flags["b"] is False


def test_concurrent_served_requests_each_get_correct_plan(daemon):
    sock, d = daemon
    want_rv, want_out, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-no-daemon"]
    )
    results = []

    def one():
        results.append(
            run_cli(["-input-json", f"-input={FIXTURE}",
                     f"-serve-socket={sock}"])
        )

    threads = [threading.Thread(target=one) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert len(results) == 3
    for rv, out, _err in results:
        assert rv == want_rv == 0
        assert out == want_out
    assert d._requests == 3


# --- the incremental tensorize cache -------------------------------------


def _parse_fixture():
    from kafkabalancer_tpu.codecs import get_partition_list_from_reader
    from kafkabalancer_tpu.models import default_rebalance_config
    from kafkabalancer_tpu.solvers.scan import _settle_head

    with open(FIXTURE) as fh:
        pl = get_partition_list_from_reader(fh, True, [])
    cfg = default_rebalance_config()
    _settle_head(pl, cfg, 0)
    return pl, cfg


def test_tensorize_cache_incremental_hit_matches_full_encode():
    import numpy as np

    from kafkabalancer_tpu.ops.tensorize import set_row_cache, tensorize
    from kafkabalancer_tpu.serve.cache import TensorizeRowCache

    pl, cfg = _parse_fixture()
    want_cold = tensorize(pl, cfg)  # uncached reference encode
    cache = TensorizeRowCache()
    set_row_cache(cache)
    try:
        dp1 = tensorize(pl, cfg)  # primes
        assert cache.stats()["hits"] == 0
        # one changed partition — the outer loop's steady state
        p0 = pl.partitions[0]
        p0.replicas[0], p0.replicas[1] = p0.replicas[1], p0.replicas[0]
        dp2 = tensorize(pl, cfg)  # incremental
        assert cache.stats()["hits"] == 1
        assert cache.stats()["rows_reused"] == len(pl.partitions) - 1
        set_row_cache(None)
        want_warm = tensorize(pl, cfg)  # uncached encode of mutated pl
        for f in ("weights", "replicas", "nrep_cur", "nrep_tgt", "ncons",
                  "allowed", "member", "pvalid", "bvalid", "topic_id"):
            np.testing.assert_array_equal(
                getattr(dp2, f), getattr(want_warm, f), err_msg=f
            )
        assert dp2.topics == want_warm.topics
        np.testing.assert_array_equal(dp2.broker_ids, want_warm.broker_ids)
        # and the primed pass matched the cold encode
        np.testing.assert_array_equal(dp1.replicas, want_cold.replicas)
    finally:
        set_row_cache(None)


def test_tensorize_cache_returns_independent_copies():
    import numpy as np

    from kafkabalancer_tpu.ops.tensorize import set_row_cache, tensorize
    from kafkabalancer_tpu.serve.cache import TensorizeRowCache

    pl, cfg = _parse_fixture()
    cache = TensorizeRowCache()
    set_row_cache(cache)
    try:
        tensorize(pl, cfg)
        dp_a = tensorize(pl, cfg)
        assert cache.stats()["hits"] == 1
        dp_a.replicas[:] = -7  # caller vandalism must not reach the cache
        dp_b = tensorize(pl, cfg)
        assert not np.any(dp_b.replicas == -7)
    finally:
        set_row_cache(None)


def test_tensorize_cache_misses_on_new_topic_and_universe_change():
    from kafkabalancer_tpu.ops.tensorize import set_row_cache, tensorize
    from kafkabalancer_tpu.serve.cache import TensorizeRowCache

    pl, cfg = _parse_fixture()
    cache = TensorizeRowCache()
    set_row_cache(cache)
    try:
        tensorize(pl, cfg)
        # a brand-new topic cannot be expressed in the cached vocabulary
        pl.partitions[0].topic = "freshly-minted-topic"
        dp = tensorize(pl, cfg)
        assert cache.stats()["hits"] == 0
        assert "freshly-minted-topic" in dp.topics
        # a universe change (extra broker) misses on the meta check
        dp2 = tensorize(pl, cfg, extra_brokers=(999,))
        assert cache.stats()["hits"] == 0
        assert 999 in list(dp2.broker_ids)
    finally:
        set_row_cache(None)


def test_served_fused_plan_uses_tensorize_cache(daemon):
    """End to end through the daemon: two identical -fused requests; the
    second re-tensorizes incrementally (serve.cache_hits visible in the
    hello counters) and both plans are byte-identical to in-process."""
    sock, d = daemon
    args = ["-input-json", f"-input={FIXTURE}", "-fused",
            "-fused-batch=4", "-max-reassign=4"]
    want_rv, want_out, _ = run_cli(args + ["-no-daemon"])
    rv1, out1, _ = run_cli(args + [f"-serve-socket={sock}"])
    rv2, out2, _ = run_cli(args + [f"-serve-socket={sock}"])
    assert rv1 == rv2 == want_rv == 0
    assert out1 == want_out and out2 == want_out
    # hits land in the resident session's trusted-delta cache (the
    # -input requests negotiate a v2 session); the daemon aggregates
    # them with the process-wide cache for attribution
    aggregated = (
        d.tensorize_cache.stats()["hits"]
        + d.sessions.cache_stats()["hits"]
    )
    assert aggregated >= 1


# --- the no-jax client pin ------------------------------------------------


def test_served_client_path_never_imports_jax(daemon):
    """The tentpole's raison d'être, pinned: a CLIENT process whose
    request is served by a daemon exits without importing jax or the
    solver stack — even for a -solver=tpu request (the daemon pays the
    device work)."""
    sock, _d = daemon
    code = (
        "import io, sys\n"
        "from kafkabalancer_tpu.cli import run\n"
        "rc = run(io.StringIO(), io.StringIO(), io.StringIO(),\n"
        "         ['kafkabalancer', '-input-json', '-input', "
        f"{FIXTURE!r}, '-solver=greedy', '-serve-socket={sock}'])\n"
        "assert rc == 0, f'exit {rc}'\n"
        "bad = [m for m in sys.modules if m == 'jax' "
        "or m.startswith('jax.')]\n"
        "assert not bad, f'jax imported on the client path: {bad[:3]}'\n"
        "assert 'kafkabalancer_tpu.solvers.scan' not in sys.modules\n"
        "assert 'kafkabalancer_tpu.solvers.tpu' not in sys.modules\n"
        # numpy rides the same pin: balancer.steps/costmodel defer it, and
        # a module-level regression puts ~0.1 s back into EVERY forwarded
        # invocation's startup
        "assert 'numpy' not in sys.modules, 'numpy on the client path'\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


# --- device lanes: the multi-lane scheduler -------------------------------


def _mk_req(name, bucket=None):
    from kafkabalancer_tpu.serve.daemon import PlanRequest

    req = PlanRequest([name], None)
    req.bucket = bucket
    req.bucketed = True
    return req


def test_lane_scheduler_affinity_and_least_loaded_routing():
    """Bucket affinity: the first request of a bucket routes to the
    least-loaded lane and later same-bucket requests stick to it even
    when the other lane is emptier."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    release = threading.Event()
    handled = []  # (name, lane index)
    lock = threading.Lock()

    def handle(req, coalesced, lane, mb):
        if req.argv[0].startswith("block"):
            release.wait(20)
        with lock:
            handled.append((req.argv[0], lane.index))
        req.response = {"ok": True}

    buckets = {"block-a": (8, 2, 4, True), "a2": (8, 2, 4, True),
               "b": (16, 2, 4, True)}
    sched = LaneScheduler(
        handle, lambda r: buckets.get(r.argv[0]),
        [Lane(0), Lane(1)],
    )
    try:
        results = []
        threads = []

        def submit(name, bucket):
            req = _mk_req(name, bucket)
            results.append(sched.submit(req))

        # blocker claims a lane for bucket A
        threads.append(
            threading.Thread(target=submit, args=("block-a", buckets["block-a"]))
        )
        threads[0].start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(sched._active):
            time.sleep(0.01)
        # same-bucket follower must queue on the SAME lane (affinity),
        # not the idle one; distinct bucket takes the idle lane
        threads.append(
            threading.Thread(target=submit, args=("a2", buckets["a2"]))
        )
        threads.append(threading.Thread(target=submit, args=("b", buckets["b"])))
        for t in threads[1:]:
            t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(handled) < 2:
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(10)
        lanes_of = dict(handled)
        assert lanes_of["block-a"] == lanes_of["a2"], handled
        assert lanes_of["b"] != lanes_of["block-a"], handled
        assert sched.busy() is False
    finally:
        release.set()
        sched.stop()


def test_lane_scheduler_steals_distinct_bucket_work():
    """An idle lane steals queued work of a DIFFERENT bucket from a busy
    lane's queue; a same-bucket run within the microbatch width stays
    put (it will drain as one fused/coalesced group)."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    release = threading.Event()
    handled = []
    lock = threading.Lock()

    def handle(req, coalesced, lane, mb):
        if req.argv[0].startswith("block"):
            release.wait(20)
        with lock:
            handled.append((req.argv[0], lane.index))
        req.response = {"ok": True}

    A, B = (8, 2, 4, True), (16, 2, 4, True)
    sched = LaneScheduler(
        handle, lambda r: None, [Lane(0), Lane(1)], microbatch=4
    )
    try:
        results = []

        def submit(req):
            results.append(sched.submit(req))

        # force everything onto lane 0 by pre-claiming affinity
        with sched._cv:
            sched._affinity[A] = 0
            sched._affinity[B] = 0
        threads = [
            threading.Thread(target=submit, args=(_mk_req("block-1", A),))
        ]
        threads[0].start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not sched._active[0]:
            time.sleep(0.01)
        # queue a same-bucket follower + a distinct-bucket request on
        # the busy lane; lane 1 is idle and may only steal the latter
        for name, b in (("a2", A), ("b1", B)):
            t = threading.Thread(target=submit, args=(_mk_req(name, b),))
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(
            n == "b1" for n, _ln in handled
        ):
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(10)
        lanes_of = dict(handled)
        assert lanes_of["b1"] == 1, handled  # stolen by the idle lane
        assert lanes_of["a2"] == 0, handled  # same-bucket run stayed
        assert sched.steals == 1
    finally:
        release.set()
        sched.stop()


def test_multi_lane_daemon_not_idle_while_lane_in_flight(
    sock_dir, monkeypatch
):
    """Idle-timeout vs in-flight lanes: a daemon with a long-running
    request on one lane and empty queues elsewhere must NOT idle-shutdown
    until all lanes drain — the 'long-running plan is not idleness'
    guarantee extended to the multi-lane scheduler."""
    from kafkabalancer_tpu import cli

    started = threading.Event()
    real_run = cli.run

    def slow_run(i, o, e, args, **kw):
        started.set()
        time.sleep(2.5)
        return real_run(i, o, e, args, **kw)

    monkeypatch.setattr(cli, "run", slow_run)
    sock = os.path.join(sock_dir, "kb.sock")
    # microbatch=2 forces the LaneScheduler even on one visible device
    d = Daemon(
        sock, idle_timeout=1.0, warm=False, log=lambda _m: None,
        lanes=0, microbatch=2,
    )
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    from kafkabalancer_tpu.serve.lanes import LaneScheduler

    assert isinstance(d._coalescer, LaneScheduler)
    result_box = []

    def one():
        result_box.append(
            sclient.forward_plan(
                sock, ["-no-daemon=true", "-input-json=true"],
                open(FIXTURE).read(),
            )
        )

    rt = threading.Thread(target=one)
    rt.start()
    assert started.wait(10), "request never started"
    # the request sleeps well past the 1.0s idle timeout; the daemon
    # must still be alive and must serve the request to completion
    time.sleep(1.6)
    assert t.is_alive(), "daemon idle-shutdown with a lane in flight"
    rt.join(30)
    assert result_box and result_box[0] is not None
    assert result_box[0].rc == 0
    t.join(15)  # now genuinely idle: the timeout may fire
    assert rc_box == [0]


# --- cross-request microbatching ------------------------------------------


def test_microbatch_group_differential_bit_parity():
    """The tentpole differential pin: two DISTINCT same-bucket instances
    fused through the microbatch barrier produce byte-identical plans to
    solo dispatches."""
    import copy

    from kafkabalancer_tpu.serve.lanes import MicrobatchGroup
    from kafkabalancer_tpu.solvers import scan

    def load(mutate=False):
        from kafkabalancer_tpu.codecs import get_partition_list_from_reader
        from kafkabalancer_tpu.models import default_rebalance_config

        with open(FIXTURE) as fh:
            pl = get_partition_list_from_reader(fh, True, [])
        if mutate:  # distinct instance, same shape bucket
            p0 = pl.partitions[0]
            p0.replicas[0], p0.replicas[1] = p0.replicas[1], p0.replicas[0]
        cfg = default_rebalance_config()
        return pl, cfg

    def emit(opl):
        out = io.StringIO()
        from kafkabalancer_tpu.codecs import write_partition_list

        write_partition_list(out, opl)
        return out.getvalue()

    solo = []
    for mutate in (False, True):
        pl, cfg = load(mutate)
        solo.append(emit(scan.plan(pl, cfg, 4, batch=4)))

    mb = MicrobatchGroup(2)
    fused = [None, None]

    def member(idx, mutate):
        pl, cfg = load(mutate)
        with mb.member():
            fused[idx] = emit(scan.plan(pl, cfg, 4, batch=4))

    threads = [
        threading.Thread(target=member, args=(0, False)),
        threading.Thread(target=member, args=(1, True)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert fused[0] == solo[0]
    assert fused[1] == solo[1]
    assert mb.fused_requests == 2
    assert mb.fused_dispatches >= 1


def test_microbatch_member_leaving_releases_the_barrier():
    """A member that never dispatches (greedy request, error exit) must
    not wedge the barrier: the remaining member's round completes and —
    as a singleton — runs solo."""
    from kafkabalancer_tpu.serve.lanes import MicrobatchGroup

    mb = MicrobatchGroup(2)
    out = []

    def leaver():
        with mb.member():
            time.sleep(0.1)  # never dispatches

    def dispatcher():
        with mb.member():
            out.append(
                mb.dispatch(
                    (None,), {"engine": "xla", "leader": False}
                )
            )

    threads = [
        threading.Thread(target=leaver),
        threading.Thread(target=dispatcher),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(20)
    assert out == [None]  # solo fallback, no deadlock


def test_microbatch_declines_non_xla_and_leader_dispatches():
    from kafkabalancer_tpu.serve.lanes import MicrobatchGroup

    mb = MicrobatchGroup(1)
    assert mb.dispatch((None,), {"engine": "pallas", "leader": False}) is None
    assert mb.dispatch((None,), {"engine": "xla", "leader": True}) is None


def test_served_microbatched_plans_byte_identical(sock_dir):
    """End to end through a continuously-batching daemon: concurrent
    same-bucket -fused requests form ONE full batch — deterministically,
    via the injectable admission hold (the lane holds its pop until the
    batch depth is queued; no scheduler-timing luck, no wave retries) —
    and every response is byte-identical to the in-process plan; a
    malformed request riding alongside still error-exits identically."""
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(
        sock, idle_timeout=60.0, warm=False, log=lambda _m: None,
        lanes=0, microbatch=4,
    )
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    try:
        args = ["-input-json", f"-input={FIXTURE}", "-fused",
                "-fused-batch=4", "-max-reassign=4"]
        want_rv, want_out, _ = run_cli(args + ["-no-daemon"])
        bad_rv, bad_out, _ = run_cli(["-input-json", "-no-daemon"], "::x::")
        # warm request: pays the compile (and establishes the bucket's
        # lane affinity) before the held batch forms
        rv0, out0, _ = run_cli(args + [f"-serve-socket={sock}"])
        assert rv0 == want_rv == 0 and out0 == want_out

        # the deterministic admission latch (satellite of the continuous
        # batcher): the affinity lane holds its pop until all 4
        # same-bucket requests are queued, so the batch forms fully on
        # the first (and only) wave
        sched = d._coalescer
        sched._hold_window_s = 30.0
        sched._hold_n = 4

        lock = threading.Lock()
        results: list = []

        def good():
            r = run_cli(args + [f"-serve-socket={sock}"])
            with lock:
                results.append(("good", r))

        def bad():
            r = run_cli(["-input-json", f"-serve-socket={sock}"], "::x::")
            with lock:
                results.append(("bad", r))

        threads = [threading.Thread(target=good) for _ in range(4)]
        threads.append(threading.Thread(target=bad))
        for x in threads:
            x.start()
        for x in threads:
            x.join(120)
        assert len(results) == 5
        for kind, (rv, out, _err) in results:
            if kind == "good":
                assert rv == 0 and out == want_out
            else:
                assert rv == bad_rv == 2 and out == bad_out
        stats = sched.stats()
        assert stats["lanes"] >= 1.0
        # the held batch fused: members rode batched dispatches, and the
        # occupancy histogram saw a multi-member round
        assert stats["microbatched"] >= 2.0, stats
        assert stats["occupancy_max"] >= 2.0, stats
    finally:
        sclient.request_shutdown(sock)
        t.join(15)
    assert rc_box == [0]


# --- continuous batching: variable-K padding + admission lifecycle --------


def _load_variant(i=None):
    """The fixture, optionally with partition ``i``'s replicas swapped —
    a DISTINCT instance in the same shape bucket (what concurrent
    clusters look like to the batcher)."""
    from kafkabalancer_tpu.codecs import get_partition_list_from_reader
    from kafkabalancer_tpu.models import default_rebalance_config

    with open(FIXTURE) as fh:
        pl = get_partition_list_from_reader(fh, True, [])
    if i is not None:
        p = pl.partitions[i % len(pl.partitions)]
        p.replicas[0], p.replicas[1] = p.replicas[1], p.replicas[0]
    return pl, default_rebalance_config()


def _emit_plan(opl):
    from kafkabalancer_tpu.codecs import write_partition_list

    out = io.StringIO()
    write_partition_list(out, opl)
    return out.getvalue()


def test_continuous_batcher_bit_parity_every_occupancy():
    """The variable-K pin: at EVERY occupancy 1..K, each member's plan
    through the continuous batcher is byte-identical to its solo plan —
    padded slots (occupancy 3 rides the K=4 executable) change nothing
    for live slots, and occupancy 1 degrades to the solo dispatch."""
    from kafkabalancer_tpu.serve.lanes import ContinuousBatcher
    from kafkabalancer_tpu.solvers import scan

    K = 4
    solo = []
    for v in range(K):
        pl, cfg = _load_variant(v if v else None)
        solo.append(_emit_plan(scan.plan(pl, cfg, 4, batch=4)))

    for n in range(1, K + 1):
        cb = ContinuousBatcher(K)
        fused = [None] * n

        def member(idx):
            pl, cfg = _load_variant(idx if idx else None)
            with cb.member():
                fused[idx] = _emit_plan(scan.plan(pl, cfg, 4, batch=4))

        threads = [
            threading.Thread(target=member, args=(idx,)) for idx in range(n)
        ]
        for t in threads:
            cb.admit()
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert fused == solo[:n], f"occupancy {n}"
        if n == 1:
            assert cb.fused_dispatches == 0  # singleton round runs solo
        else:
            assert cb.fused_dispatches >= 1, f"occupancy {n}"
            assert cb.occupancy.get(n) == 1, (n, cb.occupancy)
            # occupancy 3 pads into the K=4 bucket; 2 and 4 fit exactly
            assert cb.padded_slots == (1 if n == 3 else 0), (
                n, cb.padded_slots,
            )


def test_continuous_batcher_solo_fast_path_counter():
    """The occupancy-adaptive pin (BENCH_r06's continuous_vs_oneshot =
    0.89x was the padded-dispatch tax at occupancy 1): a sole live
    member's dispatch is declined inline — counted in ``solo_fast``,
    zero fused dispatches, plan bytes identical to the oneshot path."""
    from kafkabalancer_tpu.serve.lanes import ContinuousBatcher
    from kafkabalancer_tpu.solvers import scan

    pl, cfg = _load_variant(None)
    oneshot = _emit_plan(scan.plan(pl, cfg, 4, batch=4))
    cb = ContinuousBatcher(4)
    for _ in range(3):
        cb.admit()
        with cb.member():
            pl, cfg = _load_variant(None)
            got = _emit_plan(scan.plan(pl, cfg, 4, batch=4))
        assert got == oneshot
    assert cb.solo_fast >= 3
    assert cb.fused_dispatches == 0
    assert cb.padded_slots == 0


def test_lane_scheduler_stats_carry_solo_fast():
    """The telemetry seam: LaneScheduler.stats() exposes the fast-path
    engagement count (unit-pinned here; the daemon scrape copies only
    its own named keys, so the scrape schema is untouched)."""
    from kafkabalancer_tpu.serve import lanes as lanes_mod

    sched = lanes_mod.LaneScheduler(
        lambda req, coalesced, lane, mb: None,
        lambda r: None,
        [lanes_mod.Lane(0)],
    )
    try:
        st = sched.stats()
        assert st["solo_fast"] == 0.0
        sched.solo_fast = 7
        assert sched.stats()["solo_fast"] == 7.0
    finally:
        sched.stop()


def test_continuous_batcher_bucket_boundary_promotion():
    """The padding-bucket transition: a 3-member wave rides the K=4
    bucket (1 padded slot), a later 5-member wave on the SAME batcher
    promotes to K=8 (3 padded slots) — every member still byte-identical
    to solo across the boundary."""
    from kafkabalancer_tpu.serve.lanes import ContinuousBatcher
    from kafkabalancer_tpu.solvers import scan

    solo = []
    for v in range(5):
        pl, cfg = _load_variant(v if v else None)
        solo.append(_emit_plan(scan.plan(pl, cfg, 2, batch=2)))

    cb = ContinuousBatcher(8)
    fused = {}
    lock = threading.Lock()

    def member(idx):
        pl, cfg = _load_variant(idx if idx else None)
        with cb.member():
            out = _emit_plan(scan.plan(pl, cfg, 2, batch=2))
        with lock:
            fused[idx] = out

    def wave(indices):
        threads = [
            threading.Thread(target=member, args=(i,)) for i in indices
        ]
        for _ in threads:
            cb.admit()
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)

    wave(range(3))  # occupancy 3 -> K=4
    assert cb.occupancy.get(3) == 1, cb.occupancy
    assert cb.padded_slots == 1
    wave(range(5))  # occupancy 5 -> K=8, same batcher, slots re-formed
    assert cb.occupancy.get(5) == 1, cb.occupancy
    assert cb.padded_slots == 1 + 3
    for idx in range(5):
        assert fused[idx] == solo[idx], f"member {idx}"


def test_continuous_batcher_mid_session_admission():
    """Iteration-level admission: member B is admitted AFTER member A's
    chunk-1 round (A runs a 2-chunk session), so B's chunk 1 fuses with
    A's chunk 2 — and both move logs stay byte-identical to their solo
    dispatches. This is the barrier-removal pin: under the one-shot
    barrier B would have waited for A's whole session."""
    from kafkabalancer_tpu.serve.lanes import ContinuousBatcher
    from kafkabalancer_tpu.solvers import scan

    # A: max_reassign=6 at chunk_moves=2 -> two dispatch rounds;
    # B: max_reassign=2 -> one round, same statics/shape signature
    pl, cfg = _load_variant(None)
    solo_a = _emit_plan(scan.plan(pl, cfg, 6, batch=4, chunk_moves=2))
    pl, cfg = _load_variant(1)
    solo_b = _emit_plan(scan.plan(pl, cfg, 2, batch=4, chunk_moves=2))

    class FirstOfferSignal(ContinuousBatcher):
        def __init__(self, max_k):
            super().__init__(max_k)
            self.first_offer_done = threading.Event()

        def dispatch(self, args, statics):
            out = super().dispatch(args, statics)
            self.first_offer_done.set()
            return out

    cb = FirstOfferSignal(4)
    fused = [None, None]

    def run_a():
        pl, cfg = _load_variant(None)
        with cb.member():
            fused[0] = _emit_plan(
                scan.plan(pl, cfg, 6, batch=4, chunk_moves=2)
            )

    def run_b():
        pl, cfg = _load_variant(1)
        with cb.member():
            fused[1] = _emit_plan(
                scan.plan(pl, cfg, 2, batch=4, chunk_moves=2)
            )

    ta = threading.Thread(target=run_a)
    cb.admit()
    ta.start()
    # A's chunk-1 offer fires as a singleton round (solo); only THEN is
    # B admitted — a true mid-session arrival
    assert cb.first_offer_done.wait(60), "A never offered chunk 1"
    tb = threading.Thread(target=run_b)
    cb.admit()
    tb.start()
    ta.join(120)
    tb.join(120)
    assert fused[0] == solo_a
    assert fused[1] == solo_b
    # the mid-flight admission really fused: one 2-member round
    assert cb.fused_dispatches >= 1
    assert cb.occupancy.get(2, 0) >= 1, cb.occupancy


def test_lane_scheduler_mesh_exclusive_drains_and_holds():
    """A mesh-exclusive request (the daemon's -fused-shard prediction:
    the sharded session owns EVERY device) must (a) wait for in-flight
    work on other lanes to drain before it dispatches, and (b) hold
    every lane's pop loop closed while it runs — nothing lane-pinned
    may race the mesh collectives, and nothing new starts until the
    mesh is released."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    release_block = threading.Event()
    excl_started = threading.Event()
    excl_release = threading.Event()
    handled = []
    lock = threading.Lock()

    def handle(req, coalesced, lane, mb):
        name = req.argv[0]
        if name == "block":
            release_block.wait(20)
        if name == "mesh":
            excl_started.set()
            excl_release.wait(20)
        with lock:
            handled.append(name)
        req.response = {"ok": True}

    sched = LaneScheduler(
        handle, lambda r: None, [Lane(0), Lane(1)],
        exclusive=lambda r: r.argv[0] == "mesh",
    )
    try:
        results = []

        def submit(req):
            results.append(sched.submit(req))

        t_block = threading.Thread(target=submit, args=(_mk_req("block"),))
        t_block.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(sched._active):
            time.sleep(0.01)
        # exclusive submitted while the blocker is in flight on the
        # other lane: it must park, not dispatch
        t_mesh = threading.Thread(target=submit, args=(_mk_req("mesh"),))
        t_mesh.start()
        assert not excl_started.wait(0.5), (
            "exclusive dispatched while another lane had in-flight work"
        )
        # a later normal request must not start while the mesh is
        # draining (parked) ...
        t_late = threading.Thread(target=submit, args=(_mk_req("late"),))
        t_late.start()
        time.sleep(0.3)
        with lock:
            assert "late" not in handled
        release_block.set()
        assert excl_started.wait(10), "exclusive never ran after drain"
        # ... nor while the exclusive OWNS the mesh
        time.sleep(0.3)
        with lock:
            assert "late" not in handled, handled
        excl_release.set()
        for t in (t_block, t_mesh, t_late):
            t.join(20)
        assert handled == ["block", "mesh", "late"]
        assert all(r.get("ok") for r in results)
        assert sched.mesh_exclusive == 1
        assert sched.stats()["mesh_exclusive"] == 1.0
        assert sched.busy() is False
    finally:
        release_block.set()
        excl_release.set()
        sched.stop()


def test_lane_scheduler_mesh_exclusive_shutdown_answers_not_runs():
    """stop() arriving while an exclusive request is still PARKED must
    answer it with a structured shutdown error, never dispatch it —
    running a mesh-wide collective beside still-in-flight lane work is
    exactly the race the drain exists to prevent."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    release_block = threading.Event()
    handled = []
    lock = threading.Lock()

    def handle(req, coalesced, lane, mb):
        name = req.argv[0]
        if name == "block":
            release_block.wait(20)
        with lock:
            handled.append(name)
        req.response = {"ok": True}

    sched = LaneScheduler(
        handle, lambda r: None, [Lane(0), Lane(1)],
        exclusive=lambda r: r.argv[0] == "mesh",
    )
    try:
        results = []

        def submit(req):
            results.append(sched.submit(req))

        t_block = threading.Thread(target=submit, args=(_mk_req("block"),))
        t_block.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(sched._active):
            time.sleep(0.01)
        t_mesh = threading.Thread(target=submit, args=(_mk_req("mesh"),))
        t_mesh.start()
        # let the exclusive reach its park (popped, waiting for drain)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(sched._excl_parked):
            time.sleep(0.01)
        assert any(sched._excl_parked)
        # shutdown while parked: the blocker finishes, the exclusive
        # must be ANSWERED, not dispatched
        stopper = threading.Thread(target=sched.stop)
        stopper.start()
        time.sleep(0.1)
        release_block.set()
        t_block.join(20)
        t_mesh.join(20)
        stopper.join(20)
        with lock:
            assert "mesh" not in handled, handled
        assert len(results) == 2
        by_ok = {bool(r.get("ok")): r for r in results}
        assert by_ok[True]["ok"] is True            # the blocker's plan
        assert "shutting down" in by_ok[False]["error"]
        assert sched.mesh_exclusive == 0  # never counted as a run
    finally:
        release_block.set()
        sched.stop()


def test_daemon_fused_shard_scheduling_predictions():
    """The daemon-side argv predictions for -fused-shard requests: NOT
    admissible for continuous batching (a mesh owner can never fuse
    with lane peers), and mesh-EXCLUSIVE for the lane scheduler (it
    must drain the fleet before dispatching)."""
    from kafkabalancer_tpu.serve.daemon import Daemon

    shard = _mk_req("x")
    shard.argv = ["-fused=true", "-fused-shard=true"]
    plain = _mk_req("y")
    plain.argv = ["-fused=true"]
    assert Daemon._admissible_request(shard) is False
    assert Daemon._admissible_request(plain) is True
    assert Daemon._mesh_exclusive_request(shard) is True
    assert Daemon._mesh_exclusive_request(plain) is False


def test_lane_scheduler_admission_hold_forms_full_batch():
    """The deterministic admission latch: with -serve-admission-hold=2
    semantics installed, a lone admissible request is NOT dispatched
    until a second one queues (or the hold window expires) — the seam
    the e2e batching test and the gate smoke key off."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    handled = []
    lock = threading.Lock()

    def handle(req, coalesced, lane, mb):
        with lock:
            handled.append((req.argv[0], mb is not None))
        req.response = {"ok": True}

    B = (8, 2, 4, True)
    sched = LaneScheduler(
        handle, lambda r: B, [Lane(0)], microbatch=4,
        admissible=lambda r: True, admission_hold=2,
    )
    sched._hold_window_s = 20.0
    try:
        results = []

        def submit(name):
            results.append(sched.submit(_mk_req(name, B)))

        t1 = threading.Thread(target=submit, args=("r1",))
        t1.start()
        time.sleep(0.4)
        # held: the lone request must still be queued, not dispatched
        assert handled == [], handled
        t2 = threading.Thread(target=submit, args=("r2",))
        t2.start()
        t1.join(20)
        t2.join(20)
        assert len(results) == 2 and all(r["ok"] for r in results)
        # both members went through the batcher together
        assert {n for n, _ in handled} == {"r1", "r2"}
        assert all(got_mb for _n, got_mb in handled), handled
    finally:
        sched.stop()


def test_admission_hold_counts_only_batchable_requests():
    """A non-batchable request interleaving must not release the latch
    as a phantom batch member: with hold=2 and [fused, greedy] queued,
    the lane stays held until a SECOND batchable request arrives."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    handled = []
    lock = threading.Lock()

    def handle(req, coalesced, lane, mb):
        with lock:
            handled.append(req.argv[0])
        req.response = {"ok": True}

    B = (8, 2, 4, True)
    sched = LaneScheduler(
        handle, lambda r: B, [Lane(0)], microbatch=4,
        admissible=lambda r: not r.argv[0].startswith("greedy"),
        admission_hold=2,
    )
    sched._hold_window_s = 20.0
    try:
        results = []

        def submit(name):
            results.append(sched.submit(_mk_req(name, B)))

        threads = [threading.Thread(target=submit, args=("fused-1",))]
        threads[0].start()
        time.sleep(0.15)
        threads.append(threading.Thread(target=submit, args=("greedy-x",)))
        threads[1].start()
        time.sleep(0.4)
        # [fused-1, greedy-x] queued: batchable count is 1 < 2 — held
        assert handled == [], handled
        threads.append(threading.Thread(target=submit, args=("fused-2",)))
        threads[2].start()
        for t in threads:
            t.join(25)
        assert len(results) == 3 and all(r["ok"] for r in results)
        assert set(handled) == {"fused-1", "greedy-x", "fused-2"}
    finally:
        sched.stop()


def test_continuous_pull_is_queue_head_prefix_only():
    """FIFO fairness of mid-flight admission: the feed stops at the
    first non-batchable/different-bucket request — a newer same-bucket
    arrival queued BEHIND it is not leapfrogged into the running
    batch."""
    from kafkabalancer_tpu.serve.daemon import PlanRequest
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    B = (8, 2, 4, True)
    sched = LaneScheduler(
        lambda req, c, ln, mb: None, lambda r: r.bucket, [Lane(0)],
        microbatch=4,
        admissible=lambda r: not r.argv[0].startswith("greedy"),
    )
    try:
        lane = sched.lanes[0]
        a = _mk_req("fused-a", B)
        g = _mk_req("greedy-x", B)
        b = _mk_req("fused-b", B)
        # stop the worker from draining while we inspect the pull
        with sched._cv:
            sched._stop = True
        sched._queues[0].extend([a, g, b])
        pulled = sched._pull_admissible(lane, B)
        assert pulled == [], pulled  # _stop gates the feed entirely
        sched._stop = False
        pulled = sched._pull_admissible(lane, B)
        # prefix only: fused-a comes out, greedy-x blocks fused-b
        assert [r.argv[0] for r in pulled] == ["fused-a"]
        assert [r.argv[0] for r in sched._queues[0]] == [
            "greedy-x", "fused-b",
        ]
        with sched._cv:
            sched._active[0] -= len(pulled)  # undo the claim accounting
            sched._queues[0].clear()
    finally:
        sched.stop()


def test_residency_pool_thread_pin_cap_releases_oldest():
    """A long session's per-round transients must not pin unbounded
    device memory: past THREAD_PIN_CAP pins, the oldest release (stay
    pooled, evictable) while the freshest stay pinned."""
    from kafkabalancer_tpu.serve.residency import (
        THREAD_PIN_CAP,
        ResidencyPool,
    )

    pool = ResidencyPool(cap=1000)
    for i in range(THREAD_PIN_CAP + 8):
        pool.put(("k", i), object())
    stats = pool.stats()
    assert stats["entries"] == THREAD_PIN_CAP + 8
    assert stats["referenced"] == THREAD_PIN_CAP  # oldest 8 released
    # the released (unpinned) prefix is evictable; the pinned tail is not
    pool._cap = 4
    pool._evict_locked()
    assert pool.stats()["entries"] == THREAD_PIN_CAP
    assert ("k", 0) not in pool
    assert ("k", THREAD_PIN_CAP + 7) in pool
    pool.release_thread()
    pool._evict_locked()
    assert pool.stats()["entries"] == 4


def test_admission_hold_skips_non_admissible_head():
    """A request the admission predictor rejects (greedy solver,
    malformed input) never waits behind the latch."""
    from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

    handled = threading.Event()

    def handle(req, coalesced, lane, mb):
        handled.set()
        req.response = {"ok": True}

    sched = LaneScheduler(
        handle, lambda r: None, [Lane(0)], microbatch=4,
        admissible=lambda r: False, admission_hold=4,
    )
    sched._hold_window_s = 20.0
    try:
        t0 = time.monotonic()
        resp = sched.submit(_mk_req("plain", None))
        assert resp["ok"] and handled.is_set()
        assert time.monotonic() - t0 < 5.0  # no hold-window wait
    finally:
        sched.stop()


# --- the shared residency pool (serve/residency.py) ------------------------


def test_residency_pool_shares_across_requests_and_refcounts():
    import numpy as np

    from kafkabalancer_tpu.ops import aot
    from kafkabalancer_tpu.serve.residency import ResidencyPool

    pool = ResidencyPool(cap=8)
    a = np.arange(32.0)
    b = np.arange(8.0)
    aot.set_staging_cache(pool)
    try:
        staged1 = aot._stage_args((a, None, b))
        assert staged1 is not None and staged1[1] is None
        assert pool.stats()["uploads"] == 2
        # a SECOND request over identical content: hits, same buffers,
        # no new uploads — the cross-request sharing the pool exists for
        staged2 = aot._stage_args((np.arange(32.0), None, np.arange(8.0)))
        assert staged2[0] is staged1[0]
        assert staged2[2] is staged1[2]
        assert pool.stats()["uploads"] == 2
        assert pool.stats()["hits"] == 2
    finally:
        aot.set_staging_cache(None)
    # this thread pinned the entries; a full cache may not evict them
    pool._cap = 1
    pool.put(("other",), object(), retain=False)
    pool._evict_locked()
    assert ("other",) not in pool  # the unpinned entry went first
    assert pool.stats()["entries"] == 2  # pinned survivors
    pool.release_thread()
    assert pool.stats()["entries"] == 1  # now evictable past the cap


def test_stage_host_arrays_publishes_into_pool_unpinned():
    import numpy as np

    from kafkabalancer_tpu.ops import aot
    from kafkabalancer_tpu.serve.residency import ResidencyPool

    pool = ResidencyPool()
    a = np.arange(16.0)
    assert aot.stage_host_arrays(pool, (a, None)) == 1
    assert len(pool) == 1
    assert pool.stats()["referenced"] == 0  # stage thread holds no pin
    # re-staging identical content is a no-op
    assert aot.stage_host_arrays(pool, (a,)) == 0


def test_dev_cached_asarray_pool_is_content_keyed():
    """The pool generalization of the per-session device cache: keys are
    pure content, so identical arrays share one upload ACROSS slot names
    (and thus across sessions/requests), unlike the dict cache."""
    import numpy as np

    from kafkabalancer_tpu.serve.residency import ResidencyPool
    from kafkabalancer_tpu.solvers.scan import _dev_cached_asarray

    pool = ResidencyPool()
    a = np.arange(16.0)
    dev1 = _dev_cached_asarray(pool, "weights", a)
    dev2 = _dev_cached_asarray(pool, "ew", np.arange(16.0))
    assert dev2 is dev1  # same content, different slot: one upload
    assert pool.stats()["uploads"] == 1 and pool.stats()["hits"] == 1
    dev3 = _dev_cached_asarray(pool, "weights", np.arange(16.0) * 3)
    assert dev3 is not dev1
    np.testing.assert_array_equal(np.asarray(dev3), np.arange(16.0) * 3)


def test_served_requests_report_residency_gauge(sock_dir):
    """The acceptance gauge: a served request through a lane daemon
    carries serve.residency_hits in its -metrics-json line."""
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(
        sock, idle_timeout=60.0, warm=False, log=lambda _m: None,
        lanes=0, microbatch=4,
    )
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    try:
        mpath = os.path.join(sock_dir, "res.metrics.json")
        rv, _out, _err = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-fused",
             "-max-reassign=2", f"-serve-socket={sock}",
             f"-metrics-json={mpath}"]
        )
        assert rv == 0
        with open(mpath) as f:
            g = json.load(f)["gauges"]
        assert g["served"] is True
        assert "serve.residency_hits" in g
        assert "serve.mb_padded_slots" in g
        # hello carries the pool and occupancy attribution for operators
        hello = sclient.daemon_alive(sock)
        assert "residency" in hello and "hits" in hello["residency"]
        assert "mb_occupancy" in hello
        assert hello["batch_mode"] == "continuous"
    finally:
        sclient.request_shutdown(sock)
        t.join(15)
    assert rc_box == [0]


def test_oneshot_batch_mode_keeps_fixed_membership_barrier(sock_dir):
    """-serve-batch-mode=oneshot: the control daemon still serves and
    fuses through the fixed-membership MicrobatchGroup (the measured
    baseline bench.py compares continuous batching against)."""
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(
        sock, idle_timeout=60.0, warm=False, log=lambda _m: None,
        lanes=0, microbatch=4, batch_mode="oneshot",
    )
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    try:
        args = ["-input-json", f"-input={FIXTURE}", "-fused",
                "-fused-batch=4", "-max-reassign=4"]
        want_rv, want_out, _ = run_cli(args + ["-no-daemon"])
        rv0, out0, _ = run_cli(args + [f"-serve-socket={sock}"])
        assert rv0 == want_rv == 0 and out0 == want_out
        assert d._coalescer._batch_mode == "oneshot"
        hello = sclient.daemon_alive(sock)
        assert hello["batch_mode"] == "oneshot"
    finally:
        sclient.request_shutdown(sock)
        t.join(15)
    assert rc_box == [0]


# --- structured protocol error frames -------------------------------------


def test_daemon_answers_bad_frames_with_error_frame(daemon):
    """An oversized length prefix or an unparseable payload gets a
    structured op-'error' response instead of a dropped connection."""
    import socket as socket_mod
    import struct

    sock_path, _d = daemon
    # oversized declared length
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.connect(sock_path)
    try:
        s.sendall(struct.pack(">I", protocol.MAX_FRAME_BYTES + 1))
        resp = protocol.read_frame(s)
        assert resp is not None and resp.get("ok") is False
        assert resp.get("op") == "error"
        assert "exceeds" in resp["error"]
    finally:
        s.close()
    # valid length, non-JSON payload
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.connect(sock_path)
    try:
        body = b"\x00not json"
        s.sendall(struct.pack(">I", len(body)) + body)
        resp = protocol.read_frame(s)
        assert resp is not None and resp.get("ok") is False
        assert resp.get("op") == "error"
    finally:
        s.close()
    # garbage argv in an otherwise valid plan frame
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.connect(sock_path)
    try:
        protocol.write_frame(
            s, {"v": protocol.PROTO_VERSION, "op": "plan", "argv": 42}
        )
        resp = protocol.read_frame(s)
        assert resp is not None and resp.get("ok") is False
        assert "argv" in resp["error"]
    finally:
        s.close()


def test_client_logs_daemon_declined_reason(sock_dir):
    """The client-side satellite pin: when the daemon positively
    declines (error frame), the CLI logs the REASON and still plans
    in-process with the correct result."""
    import socket as socket_mod

    from kafkabalancer_tpu import __version__

    sock_path = os.path.join(sock_dir, "fake.sock")
    srv = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(4)
    stop = threading.Event()

    def serve():
        while not stop.is_set():
            try:
                srv.settimeout(0.2)
                conn, _ = srv.accept()
            except socket_mod.timeout:
                continue
            except OSError:
                return
            try:
                while True:
                    msg = protocol.read_frame(conn)
                    if msg is None:
                        break
                    if msg.get("op") == "hello":
                        protocol.write_frame(conn, {
                            "v": protocol.PROTO_VERSION, "ok": True,
                            "op": "hello", "version": __version__,
                            "pid": os.getpid(),
                        })
                    else:  # decline every plan with a structured reason
                        protocol.write_frame(conn, {
                            "v": protocol.PROTO_VERSION, "ok": False,
                            "op": "error",
                            "error": "bad frame: synthetic refusal",
                        })
                        break
            except Exception:
                pass
            finally:
                conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        rv_s, out_s, err_s = run_cli(
            ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock_path}"]
        )
        rv_n, out_n, _ = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-no-daemon"]
        )
        assert rv_s == rv_n == 0
        assert out_s == out_n  # fell back in-process, byte-identical
        assert "daemon declined request (bad frame: synthetic refusal)" in err_s
        assert "planning in-process" in err_s
    finally:
        stop.set()
        srv.close()
        t.join(5)


# --- per-lane pinning seams ------------------------------------------------


def test_lane_context_installs_and_clears_thread_seams():
    from kafkabalancer_tpu.ops import aot
    from kafkabalancer_tpu.ops.tensorize import row_cache, set_row_cache
    from kafkabalancer_tpu.serve.cache import TensorizeRowCache
    from kafkabalancer_tpu.serve.lanes import Lane

    lane = Lane(0, device=None)
    lane.row_cache = TensorizeRowCache()
    assert aot.execution_device() is None
    with lane.context():
        assert aot.staging_cache() is lane.stage_cache
        assert row_cache() is lane.row_cache
    assert aot.staging_cache() is None
    assert row_cache() is None
    # the thread-local override shadows (and restores to) the global
    global_cache = TensorizeRowCache()
    set_row_cache(global_cache)
    try:
        with lane.context():
            assert row_cache() is lane.row_cache
        assert row_cache() is global_cache
    finally:
        set_row_cache(None)


def test_stage_request_primes_lane_caches(sock_dir):
    """The host-encode pipeline stage: staging a fused request fills the
    lane's digest-keyed staging cache with device-resident tensors and
    primes the lane's row cache, so the request's own dispatch reuses
    both. Host-only requests stage nothing."""
    from kafkabalancer_tpu.serve.cache import TensorizeRowCache
    from kafkabalancer_tpu.serve.daemon import PlanRequest
    from kafkabalancer_tpu.serve.lanes import Lane

    d = Daemon(
        os.path.join(sock_dir, "unused.sock"), warm=False,
        log=lambda _m: None,
    )
    lane = Lane(0, device=None)
    lane.row_cache = TensorizeRowCache()
    with open(FIXTURE) as fh:
        src = fh.read()
    req = PlanRequest(
        ["-no-daemon=true", "-input-json=true", "-fused=true",
         "-max-reassign=4"],
        src,
    )
    d._stage_request(req, lane)
    assert len(lane.stage_cache) > 0
    # the stage's tensorize pass primed the per-lane row cache
    assert lane.row_cache._meta is not None
    # a greedy request has no device dispatch to stage for
    lane2 = Lane(1, device=None)
    lane2.row_cache = TensorizeRowCache()
    d._stage_request(
        PlanRequest(["-no-daemon=true", "-input-json=true"], src), lane2
    )
    assert len(lane2.stage_cache) == 0


# --- the device-upload cache (scan._dev_cached_asarray) -------------------


def test_dev_cached_asarray_reuses_equal_content():
    import numpy as np

    from kafkabalancer_tpu.solvers.scan import _dev_cached_asarray

    cache = {}
    a1 = np.arange(16.0)
    dev1 = _dev_cached_asarray(cache, "w", a1)
    # a FRESH array with identical content (what re-tensorize produces)
    dev2 = _dev_cached_asarray(cache, "w", np.arange(16.0))
    assert dev2 is dev1  # no re-upload
    # changed content misses and replaces the slot
    a3 = np.arange(16.0) * 2
    dev3 = _dev_cached_asarray(cache, "w", a3)
    assert dev3 is not dev1
    np.testing.assert_array_equal(np.asarray(dev3), a3)
    # None passes through; no cache is a plain asarray
    assert _dev_cached_asarray(cache, "x", None) is None
    assert _dev_cached_asarray(None, "w", a1) is not None


# --- live daemon telemetry: the stats / dump-trace scrape ops --------------

GOLDEN_STATS = os.path.join(
    os.path.dirname(__file__), "data", "serve_stats_schema_v8.json"
)


def test_hello_and_stats_render_from_one_snapshot(daemon):
    """The satellite pin: hello and stats are two renderings of ONE
    shared snapshot helper — every hello state key appears in the stats
    document with the same meaning, and hello carries the new
    uptime_s/requests_inflight gauges."""
    sock, _d = daemon
    hello = sclient.daemon_alive(sock)
    assert hello["uptime_s"] >= 0.0
    assert hello["requests_inflight"] == 0
    doc = sclient.fetch_stats(sock)
    assert doc is not None
    # max_v is negotiation metadata (protocol v2), not snapshot state
    shared = set(hello) - {"v", "ok", "op", "max_v"}
    assert shared <= set(doc), shared - set(doc)
    # idle daemon: the shared counters agree between the two scrapes
    for key in ("requests", "coalesced", "requests_inflight", "pid",
                "version"):
        assert hello[key] == doc[key], key


def test_stats_scrape_reconciles_with_served_requests(daemon):
    """Acceptance pin: after traffic, the serve.request_s histogram's
    count equals serve.requests exactly, and the per-phase chain
    (read/queue/parse/plan/encode/reply) is present."""
    sock, d = daemon
    for _ in range(2):
        rv, _out, _err = run_cli(
            ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
        )
        assert rv == 0
    doc = sclient.fetch_stats(sock)
    assert doc["requests"] == d._requests == 2
    hists = doc["hists"]
    assert hists["serve.request_s"]["count"] == doc["requests"]
    for name in ("serve.phase.read", "serve.phase.queue",
                 "serve.phase.parse", "serve.phase.plan",
                 "serve.phase.encode", "serve.phase.reply"):
        assert name in hists, sorted(hists)
        assert hists[name]["count"] >= 1
        assert hists[name]["p50"] >= 0.0
        assert hists[name]["window"]["count"] >= 1  # just-served: in window
    # a -fused request adds the device-path phases
    rv, _out, _err = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-fused", "-max-reassign=2",
         f"-serve-socket={sock}"]
    )
    assert rv == 0
    hists = sclient.fetch_stats(sock)["hists"]
    for name in ("serve.phase.settle", "serve.phase.tensorize",
                 "serve.phase.dispatch"):
        assert name in hists, sorted(hists)
    # and the flight recorder holds the request summaries with phases
    resp = sclient.fetch_trace(sock)
    reqs = resp["trace"]["otherData"]["requests"]
    assert len(reqs) == 3
    assert all(r["rc"] == 0 for r in reqs)
    assert "parse" in reqs[-1]["phases"]
    assert "dispatch" in reqs[-1]["phases"]


def test_stats_scrape_never_blocks_on_inflight_plan(sock_dir, monkeypatch):
    """The tentpole's no-pause pin: with a plan request WEDGED in the
    dispatcher, stats and dump-trace still answer promptly (they run on
    the connection thread, never through the dispatcher) and report the
    request as in flight."""
    from kafkabalancer_tpu import cli as cli_mod

    started = threading.Event()
    release = threading.Event()
    real_run = cli_mod.run

    def slow_run(i, o, e, args, **kw):
        started.set()
        release.wait(30)
        return real_run(i, o, e, args, **kw)

    monkeypatch.setattr(cli_mod, "run", slow_run)
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(sock, idle_timeout=60.0, warm=False, log=lambda _m: None)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    try:
        result_box = []

        def one():
            result_box.append(
                sclient.forward_plan(
                    sock, ["-no-daemon=true", "-input-json=true"],
                    open(FIXTURE).read(),
                )
            )

        rt = threading.Thread(target=one)
        rt.start()
        assert started.wait(10), "request never started"
        t0 = time.monotonic()
        doc = sclient.fetch_stats(sock)
        trace = sclient.fetch_trace(sock)
        elapsed = time.monotonic() - t0
        assert doc is not None and trace is not None
        assert elapsed < 5.0, f"scrape stalled {elapsed:.1f}s"
        assert doc["requests_inflight"] >= 1
        release.set()
        rt.join(30)
        assert result_box and result_box[0] is not None
        assert result_box[0].rc == 0
        assert (sclient.fetch_stats(sock) or {})["requests_inflight"] == 0
    finally:
        release.set()
        sclient.request_shutdown(sock)
        t.join(15)
    assert rc_box == [0]


def test_serve_stats_json_schema_golden(daemon):
    """Golden-file pin: the stats document's top-level keys, histogram
    entry keys, per-tenant entry keys and flight keys are VERSIONED
    (kafkabalancer-tpu.serve-stats/8) — changing any requires a schema
    bump and a new golden."""
    sock, _d = daemon
    rv, _out, _err = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
    )
    assert rv == 0
    doc = sclient.fetch_stats(sock)
    with open(GOLDEN_STATS) as f:
        golden = json.load(f)
    assert doc["schema"] == golden["schema"]
    base = set(golden["top_level_keys"])
    lane = set(golden["lane_keys"])
    assert base <= set(doc) <= base | lane, sorted(doc)
    for name, h in doc["hists"].items():
        assert set(h) == set(golden["hist_keys"]), name
        assert set(h["window"]) == set(golden["hist_window_keys"]), name
        for le, n in h["buckets"]:
            assert le >= 0.0 and n >= 1
    assert set(doc["flight"]) == set(golden["flight_keys"])
    # v2: per-lane device-memory attribution, one entry per lane
    assert isinstance(doc["memory"], list) and doc["memory"]
    for entry in doc["memory"]:
        assert set(entry) == set(golden["memory_keys"]), entry
        assert entry["residency_bytes"] >= 0
        assert entry["residency_entries"] >= 0
    # v3: resident sessions + daemon-observed fallback reasons
    assert set(doc["sessions"]) == set(golden["sessions_keys"])
    assert doc["sessions"]["count"] >= 1  # the -input request registered
    assert doc["sessions"]["bytes"] > 0
    assert isinstance(doc["fallbacks"], dict)
    # v6: the warm session tier's paging block — same key set whether
    # the tier is enabled or not (this daemon has it off)
    assert set(doc["paging"]) == set(golden["paging_keys"])
    assert doc["paging"]["enabled"] is False
    # v7: speculation + watch blocks — same key set with both off
    assert set(doc["speculation"]) == set(golden["speculation_keys"])
    assert doc["speculation"]["enabled"] is False
    assert set(doc["watch"]) == set(golden["watch_keys"])
    assert doc["watch"]["enabled"] is False
    # v4: per-tenant attribution (bounded top-K label families)
    tenants = doc["tenants"]
    assert set(tenants) == set(golden["tenants_keys"])
    assert tenants["top"], "the -input request must be tenant-attributed"
    for name, entry in tenants["top"].items():
        assert set(entry) == set(golden["tenant_entry_keys"]), name
        assert entry["requests"] >= 1
        assert set(entry["request_s"]) == set(golden["hist_keys"]), name
        assert entry["request_s"]["count"] == entry["requests"]
    if tenants["other"] is not None:
        assert set(tenants["other"]) == set(golden["tenant_entry_keys"])


def test_served_explain_forwards_and_matches(daemon, sock_dir, tmp_path):
    """-explain forwards like any other flag: the daemon writes the
    document to the client's (absolutized) path, the plan bytes relay
    byte-identical to -no-daemon, and the document matches the one an
    in-process run produces (modulo the timestamp)."""
    sock, _d = daemon
    served_path = os.path.join(sock_dir, "served.explain.json")
    rv_s, out_s, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-fused", "-max-reassign=3",
         f"-serve-socket={sock}", f"-explain={served_path}"]
    )
    local_path = str(tmp_path / "local.explain.json")
    rv_l, out_l, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-fused", "-max-reassign=3",
         "-no-daemon", f"-explain={local_path}"]
    )
    assert (rv_s, out_s) == (rv_l, out_l)
    served = json.load(open(served_path))
    local = json.load(open(local_path))
    served.pop("ts_epoch"), local.pop("ts_epoch")
    assert served == local
    assert served["moves_emitted"] == len(served["moves"]) > 0


def test_core_snapshot_memory_block(daemon):
    """Per-lane device-memory attribution rides hello AND stats (the
    shared snapshot); warm=False daemon: jax never imported, so the
    jax-free-safe seam reports null HBM rather than importing it."""
    sock, _d = daemon
    hello = sclient.daemon_alive(sock)
    doc = sclient.fetch_stats(sock)
    for scrape in (hello, doc):
        mem = scrape["memory"]
        assert isinstance(mem, list) and len(mem) >= 1
        assert mem[0]["lane"] == 0
        assert mem[0]["residency_bytes"] == 0


def test_scrape_cli_verbs_roundtrip(daemon, sock_dir):
    """-serve-stats[-json], -metrics-prom and -serve-dump-trace: the
    jax-free operator verbs over a live daemon, and exit 3 with a named
    reason when none is reachable."""
    sock, _d = daemon
    rv, _out, _err = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
    )
    assert rv == 0
    rv, out, _err = run_cli([f"-serve-socket={sock}", "-serve-stats-json"])
    assert rv == 0
    doc = json.loads(out)
    assert doc["schema"] == "kafkabalancer-tpu.serve-stats/8"
    assert doc["hists"]["serve.request_s"]["count"] == doc["requests"]
    rv, out, _err = run_cli([f"-serve-socket={sock}", "-serve-stats"])
    assert rv == 0
    assert "serve stats" in out and "hist serve.request_s" in out
    rv, out, _err = run_cli([f"-serve-socket={sock}", "-metrics-prom=-"])
    assert rv == 0
    assert "# TYPE kafkabalancer_tpu_requests counter" in out
    assert 'quantile="0.99"' in out
    assert "kafkabalancer_tpu_serve_request_s_count 1" in out
    prom_path = os.path.join(sock_dir, "m.prom")
    rv, _out, _err = run_cli(
        [f"-serve-socket={sock}", f"-metrics-prom={prom_path}"]
    )
    assert rv == 0 and "kafkabalancer_tpu_" in open(prom_path).read()
    tpath = os.path.join(sock_dir, "flight.trace.json")
    rv, _out, err = run_cli(
        [f"-serve-socket={sock}", f"-serve-dump-trace={tpath}"]
    )
    assert rv == 0 and "flight trace written" in err
    with open(tpath) as f:
        trace = json.load(f)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert xs and all({"name", "ts", "dur", "pid", "tid"} <= set(e) for e in xs)
    # no daemon: a named error exit, not a crash or a silent 0
    gone = os.path.join(sock_dir, "absent.sock")
    for args in (["-serve-stats-json"], ["-serve-stats"],
                 ["-metrics-prom=-"], ["-serve-dump-trace=-"]):
        rv, out, err = run_cli([f"-serve-socket={gone}"] + args)
        assert rv == 3 and "no live daemon" in err, (args, rv, err)
    # live daemon but an unwritable LOCAL path: the output-write-failure
    # code (4), NOT the daemon-unreachable code — a monitoring wrapper
    # must not misdiagnose a full disk as a dead daemon
    bad = os.path.join(sock_dir, "no-such-dir", "out.txt")
    for flag in (f"-metrics-prom={bad}", f"-serve-dump-trace={bad}"):
        rv, _out, err = run_cli([f"-serve-socket={sock}", flag])
        assert rv == 4 and "failed writing" in err, (flag, rv, err)
    # contradictory combinations refuse loudly instead of silently
    # scraping and discarding the rest of the invocation
    rv, _out, err = run_cli(["-serve", f"-serve-socket={sock}",
                             "-serve-stats"])
    assert rv == 3 and "cannot be combined with -serve" in err
    rv, _out, err = run_cli(["-input-json", f"-input={FIXTURE}",
                             f"-serve-socket={sock}", "-serve-stats-json"])
    assert rv == 3 and "take no input" in err


def test_served_trace_writes_merged_timeline(sock_dir):
    """The ISSUE 18 tentpole, end to end: a forwarded invocation with
    -trace writes ONE merged Perfetto doc — client track + daemon
    footer track under a single trace id, daemon spans parented under
    the client's serve.forward span and never starting before it — and
    the forwarded -metrics-json line (daemon-written) carries the
    trace id + client.phase.* edge attribution. A SUBPROCESS daemon:
    stitching across two processes (two monotonic clock bases) is the
    whole point — an in-process daemon thread would share the client's
    tracer and hide alignment bugs."""
    sock = os.path.join(sock_dir, "kb.sock")
    proc = subprocess.Popen(
        [sys.executable, "-m", "kafkabalancer_tpu", "-serve",
         f"-serve-socket={sock}", "-serve-idle-timeout=120",
         "-serve-lanes=1"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            pytest.fail(f"daemon exited rc={proc.returncode} at startup")
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.05)
    else:
        proc.kill()
        pytest.fail("daemon never became ready")
    try:
        tpath = os.path.join(sock_dir, "merged.trace.json")
        mpath = os.path.join(sock_dir, "served.metrics.json")
        rv, _out, _err = run_cli(
            ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}",
             f"-trace={tpath}", f"-metrics-json={mpath}"]
        )
        assert rv == 0
        _assert_merged_timeline(sock, tpath, mpath)
    finally:
        sclient.request_shutdown(sock)
        try:
            proc.wait(15)
        except subprocess.TimeoutExpired:
            proc.kill()


def _assert_merged_timeline(sock, tpath, mpath):
    with open(tpath) as f:
        doc = json.load(f)
    other = doc["otherData"]
    assert other["served"] is True
    trace_id = other["trace_id"]
    assert len(trace_id) == 16 and int(trace_id, 16) >= 0
    # a same-host daemon handshake always yields a usable clock sample
    assert isinstance(other["clock_offset_ns"], int)
    assert other["clock_rtt_ns"] >= 0
    assert other["daemon_wall_s"] > 0.0
    events = doc["traceEvents"]
    dpid = os.getpid() + 1
    client_x = [
        e for e in events if e["ph"] == "X" and e["pid"] != dpid
    ]
    daemon_x = [
        e for e in events if e["ph"] == "X" and e["pid"] == dpid
    ]
    assert daemon_x, "the reply footer must land a daemon track"
    client_names = {e["name"] for e in client_x}
    # the edge phase chain on the client track
    for name in ("client.input_read", "client.canonicalize",
                 "client.connect", "client.handshake", "client.send",
                 "client.wait_first_byte", "client.receive"):
        assert name in client_names, sorted(client_names)
    fwd = [e for e in client_x if e["name"] == "serve.forward"]
    assert len(fwd) == 1
    assert fwd[0]["args"]["trace_id"] == trace_id
    # the wire phases opened INSIDE the forward span share its sid as
    # their parent — which is exactly the sid the daemon track must
    # parent under
    fwd_sid = next(
        e["args"]["parent_sid"] for e in client_x
        if e["name"] == "client.send"
    )
    daemon_names = {e["name"] for e in daemon_x}
    # the daemon's dispatch chain (the request thread's span subtree)
    assert {"parse_input", "plan", "emit"} <= daemon_names, sorted(
        daemon_names
    )
    for e in daemon_x:
        assert e["args"]["daemon"] is True
        assert e["args"]["trace_id"] == trace_id
        assert e["args"]["parent_sid"] == fwd_sid
        # causality: the daemon's work never precedes the forward span
        assert e["ts"] >= fwd[0]["ts"]
    # the daemon-written metrics line: trace id + edge attribution
    with open(mpath) as f:
        payload = json.load(f)
    gauges = payload["gauges"]
    assert gauges["trace_id"] == trace_id
    for key in ("client.phase.input_read", "client.phase.canonicalize",
                "client.phase.connect", "client.phase.handshake"):
        assert key in gauges and gauges[key] >= 0.0, sorted(gauges)
    assert gauges["client.edge_pre_ms"] >= 0.0
    # the daemon's flight record reconciles to the same trace id
    reqs = sclient.fetch_trace(sock)["trace"]["otherData"]["requests"]
    assert reqs[-1]["trace"] == trace_id
    # per-tenant edge attribution landed in the scrape
    doc_stats = sclient.fetch_stats(sock)
    entries = list(doc_stats["tenants"]["top"].values())
    assert any(
        isinstance(e["edge_ms"], dict) and e["edge_ms"]["count"] >= 1
        for e in entries
    ), entries


def test_served_requests_get_distinct_trace_ids(daemon):
    """Trace-less of nothing: EVERY forwarded invocation (no -trace,
    no -stats) mints a trace id, and each served request's flight
    record carries its own, distinct id."""
    sock, _d = daemon
    for _ in range(3):
        rv, _out, _err = run_cli(
            ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
        )
        assert rv == 0
    reqs = sclient.fetch_trace(sock)["trace"]["otherData"]["requests"]
    ids = [r["trace"] for r in reqs]
    assert len(ids) == 3
    assert all(isinstance(i, str) and len(i) == 16 for i in ids)
    assert len(set(ids)) == 3


def test_v1_clients_and_scrapes_see_no_trace_keys(daemon):
    """Compatibility pins: the hello reply only carries the clock block
    when the client OPTED IN (scrape hellos never do), and a v1-framed
    plan round-trips with no trace/footer keys anywhere."""
    sock, _d = daemon
    hello = sclient.daemon_alive(sock)
    assert "clock" not in hello
    # a raw v1 plan exchange: no trace context sent, none returned
    import socket as socket_mod

    conn = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    conn.connect(sock)
    try:
        protocol.write_frame(conn, {"v": 1, "op": "hello"})
        h = protocol.read_frame(conn)
        assert h["ok"] is True and "clock" not in h, sorted(h)
        protocol.write_frame(conn, {
            "v": 1, "op": "plan",
            "argv": ["-no-daemon=true", "-input-json=true"],
            "stdin": open(FIXTURE).read(),
        })
        resp = protocol.read_frame(conn)
        assert resp["ok"] is True and resp["rc"] == 0
        assert "trace" not in resp, sorted(resp)
    finally:
        conn.close()


def test_prometheus_exposition_keeps_counters_exact():
    """%g would round a 7-digit counter (rate() reads it as frozen);
    the exposition must emit integers exactly and floats at full
    precision."""
    from kafkabalancer_tpu.obs import export as obs_export

    text = obs_export.render_prometheus({
        "requests": 1234567,
        "uptime_s": 2.5,
        "hists": {
            "serve.request_s": {
                "count": 9999999, "sum": 1234567.25,
                "p50": 0.5, "p95": 1.0, "p99": 2.0,
            },
        },
    })
    assert "kafkabalancer_tpu_requests 1234567\n" in text
    assert "kafkabalancer_tpu_uptime_s 2.5\n" in text
    assert "kafkabalancer_tpu_serve_request_s_count 9999999" in text
    assert "kafkabalancer_tpu_serve_request_s_sum 1234567.25" in text
    assert "e+06" not in text
    # the incident-signal counters ride the exposition and the human
    # rendering — write-only crash/slow attribution helps nobody
    text = obs_export.render_prometheus(
        {"requests": 4, "slow_requests": 2, "crashed_requests": 1}
    )
    assert "kafkabalancer_tpu_slow_requests 2\n" in text
    assert "kafkabalancer_tpu_crashed_requests 1\n" in text
    human = obs_export.render_serve_stats(
        {"requests": 4, "slow_requests": 2, "crashed_requests": 1}
    )
    assert "2 slow" in human and "1 crashed" in human


def test_scrapes_do_not_reset_idle_clock(sock_dir):
    """Monitoring must stay passive: a daemon under periodic stats
    scrapes (and hellos) still idle-times-out; only plan work pins it
    alive."""
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(sock, idle_timeout=1.0, warm=False, log=lambda _m: None)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    # scrape well past the idle timeout; the daemon must still exit
    deadline = time.monotonic() + 20
    while t.is_alive() and time.monotonic() < deadline:
        sclient.fetch_stats(sock)
        time.sleep(0.2)
    assert not t.is_alive(), "scrapes pinned the daemon alive"
    assert rc_box == [0]


def test_scrape_verbs_never_import_jax(daemon):
    """The no-jax client pin extended to the scrape verbs: a process
    that scrapes a live daemon (stats JSON + trace dump) exits without
    importing jax, numpy or the solver stack."""
    sock, _d = daemon
    code = (
        "import io, sys\n"
        "from kafkabalancer_tpu.cli import run\n"
        "out = io.StringIO()\n"
        "rc = run(io.StringIO(), out, io.StringIO(),\n"
        f"         ['kafkabalancer', '-serve-socket={sock}',\n"
        "          '-serve-stats-json', '-serve-dump-trace=-',\n"
        "          '-metrics-prom=-'])\n"
        "assert rc == 0, f'exit {rc}'\n"
        "assert out.getvalue(), 'no scrape output'\n"
        "bad = [m for m in sys.modules if m == 'jax' "
        "or m.startswith('jax.')]\n"
        "assert not bad, f'jax imported on the scrape path: {bad[:3]}'\n"
        "assert 'kafkabalancer_tpu.solvers.scan' not in sys.modules\n"
        "assert 'numpy' not in sys.modules, 'numpy on the scrape path'\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_slow_request_autodump(sock_dir):
    """-serve-slow-ms: a served request over the threshold auto-dumps a
    Perfetto flight trace (request log riding in otherData) into the
    daemon's flight dir, and the counter says so."""
    from kafkabalancer_tpu import obs

    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(
        sock, idle_timeout=60.0, warm=False, log=lambda _m: None,
        slow_ms=0.001, flight_dir=sock_dir,
    )
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    try:
        rv, _out, _err = run_cli(
            ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
        )
        assert rv == 0
        dumps = [
            f for f in os.listdir(sock_dir)
            if f.startswith("kafkabalancer-flight-") and "slow-req" in f
        ]
        assert dumps, os.listdir(sock_dir)
        with open(os.path.join(sock_dir, dumps[0])) as f:
            doc = json.load(f)
        assert doc["traceEvents"]
        assert doc["otherData"]["requests"]
        assert d.flight.stats()["autodumps"] >= 1
        # the DURABLE outcome counter rides the scrape (daemon-lifetime
        # field — the registry counter of the same name is wiped by the
        # next request's begin_invocation in single-lane mode)
        stats = sclient.fetch_stats(sock)
        assert stats["slow_requests"] >= 1
        assert stats["crashed_requests"] == 0
        assert obs.REGISTRY.counter_get("serve.slow_requests") >= 1.0
    finally:
        sclient.request_shutdown(sock)
        t.join(15)
    assert rc_box == [0]


def test_request_gauges_resnapshot_include_own_fusion(sock_dir):
    """The PR-6 gap, fixed: a request's -metrics-json gauges are
    re-snapshotted at EXPORT time, so its own fused dispatch shows in
    its own serve.mb_occupancy_max — start-of-request snapshots could
    never see it."""
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(
        sock, idle_timeout=60.0, warm=False, log=lambda _m: None,
        lanes=0, microbatch=4,
    )
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    try:
        args = ["-input-json", f"-input={FIXTURE}", "-fused",
                "-fused-batch=4", "-max-reassign=4"]
        # warm request: compile + bucket affinity, before the held batch
        rv0, _out0, _err0 = run_cli(args + [f"-serve-socket={sock}"])
        assert rv0 == 0
        sched = d._coalescer
        sched._hold_window_s = 30.0
        sched._hold_n = 2

        lock = threading.Lock()
        gauge_lines = []

        def member(idx):
            mpath = os.path.join(sock_dir, f"fusion-{idx}.json")
            rv, _out, _err = run_cli(
                args + [f"-serve-socket={sock}", f"-metrics-json={mpath}"]
            )
            with open(mpath) as f:
                payload = json.load(f)
            with lock:
                gauge_lines.append((rv, payload["gauges"]))

        threads = [
            threading.Thread(target=member, args=(i,)) for i in range(2)
        ]
        for x in threads:
            x.start()
        for x in threads:
            x.join(120)
        assert len(gauge_lines) == 2
        for rv, g in gauge_lines:
            assert rv == 0
            assert g["served"] is True
            # EACH member's own line already shows the fusion it rode
            assert g["serve.mb_occupancy_max"] >= 2.0, g
    finally:
        sclient.request_shutdown(sock)
        t.join(15)
    assert rc_box == [0]
