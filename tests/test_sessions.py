"""Resident cluster sessions + protocol v2 (serve/sessions.py,
serve/state.py, the v2 frame layer and the daemon's session ops).

The load-bearing pins:

- the client-computed state digest equals the daemon's prediction after
  applying the daemon's own emitted moves — the entire fast path hangs
  on these two independent computations agreeing;
- the DELTA-path plan (no state shipped at all) is byte-identical to a
  full-state ``-no-daemon`` plan of the same cluster state, for every
  solver mode;
- a digest mismatch NEVER produces a wrong answer: row-level and full
  re-syncs both land byte-identical plans;
- v1 clients keep working against a v2 daemon, byte for byte;
- the session store's LRU cap and idle expiry hold under thousands of
  registered clusters.
"""

import io
import json
import os
import re
import shutil
import socket as socket_mod
import tempfile
import threading
import time

import pytest

from kafkabalancer_tpu import cli
from kafkabalancer_tpu.codecs import get_partition_list_from_reader
from kafkabalancer_tpu.serve import client as sclient
from kafkabalancer_tpu.serve import protocol
from kafkabalancer_tpu.serve import state as sstate
from kafkabalancer_tpu.serve.daemon import Daemon
from kafkabalancer_tpu.serve.sessions import (
    ClusterSession,
    SessionStore,
    flags_signature,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")

_TS = re.compile(r"^\d{4}/\d{2}/\d{2} \d{2}:\d{2}:\d{2} ", re.M)


def run_cli(args, stdin=""):
    out, err = io.StringIO(), io.StringIO()
    rv = cli.run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


def strip_ts(err: str) -> str:
    return _TS.sub("", err)


@pytest.fixture
def sock_dir():
    d = tempfile.mkdtemp(prefix="kbss-")
    yield d
    shutil.rmtree(d, ignore_errors=True)


@pytest.fixture
def daemon(sock_dir):
    sock = os.path.join(sock_dir, "kb.sock")
    d = Daemon(sock, idle_timeout=60.0, warm=False, log=lambda _m: None)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            break
        time.sleep(0.02)
    else:
        pytest.fail("daemon never became ready")
    yield sock, d
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0], rc_box


def _fixture_state() -> dict:
    with open(FIXTURE) as f:
        return json.load(f)


def _apply_plan(state: dict, plan_stdout: str) -> int:
    """The outer loop's half of the contract: apply every emitted move
    to the cluster state by topic+partition. Returns the move count."""
    plan = json.loads(plan_stdout)
    moves = plan.get("partitions") or []
    for entry in moves:
        for row in state["partitions"]:
            if (
                row["topic"] == entry["topic"]
                and row["partition"] == entry["partition"]
            ):
                row["replicas"] = list(entry["replicas"])
                break
        else:
            raise AssertionError(f"emitted move not in state: {entry}")
    return len(moves)


# --- serve/state.py: canonical digests + packed rows -----------------------


def test_client_digest_matches_daemon_snapshot():
    """The two ends of the digest handshake — the client's fast parse
    and the daemon's Partition-object snapshot — agree on every field
    the reader produces."""
    text = json.dumps({"version": 1, "partitions": [
        {"topic": "a", "partition": 0, "replicas": [1, 2]},
        {"topic": "a", "partition": 1, "replicas": [2, 3], "weight": 2.5},
        {"topic": "b", "partition": 0, "replicas": [3], "num_replicas": 2,
         "brokers": [1, 2, 3], "num_consumers": 7},
        {"topic": "b", "partition": 1, "replicas": [1], "weight": 3},
    ]})
    st = sstate.client_state(text, True, [])
    assert st is not None
    pl = get_partition_list_from_reader(text, True, [])
    sess = ClusterSession("t", "")
    sess.snapshot_from(pl)
    assert sess.digest == st.digest
    assert sess.canon == st.canon


def test_client_digest_describe_format_and_topics_filter():
    text = (
        "\tTopic: foo\tPartition: 0\tLeader: 1\tReplicas: 1,2\tIsr: 1,2\n"
        "\tTopic: bar\tPartition: 0\tLeader: 2\tReplicas: 2,3\tIsr: 2,3\n"
        "noise line\n"
    )
    st_all = sstate.client_state(text, False, [])
    st_foo = sstate.client_state(text, False, ["foo"])
    assert st_all is not None and st_foo is not None
    assert st_all.digest != st_foo.digest
    pl = get_partition_list_from_reader(text, False, ["foo"])
    sess = ClusterSession("t", "")
    sess.snapshot_from(pl)
    assert sess.digest == st_foo.digest


def test_client_digest_bails_on_bad_input():
    assert sstate.client_state("::x::", True, []) is None
    assert sstate.client_state("", False, []) is None  # empty list


def test_fast_json_path_mirrors_reader_semantics():
    """The raw-dict fast path and the codecs reader must agree row for
    row — including the reader's oddest corners: null-vs-absent
    brokers, null replicas, int weights coerced to float, and every
    type violation the reader rejects."""
    good = json.dumps({"version": 1, "partitions": [
        {"topic": "t", "partition": 0, "replicas": None, "weight": 2},
        {"topic": "t", "partition": 1, "replicas": [1], "brokers": None},
        {"topic": "t", "partition": 2, "replicas": [1, 2]},
    ]})
    st = sstate.client_state(good, True, [])
    assert st is not None
    pl = get_partition_list_from_reader(good, True, [])
    sess = ClusterSession("t", "")
    sess.snapshot_from(pl)
    assert sess.digest == st.digest
    assert sess.canon == st.canon
    # null replicas -> [] but null brokers -> [] (NOT None): the two
    # defaults differ in the reader and must differ in the digest
    assert st.rows[0][2] == [] and st.rows[1][5] == [] and st.rows[2][5] \
        is None
    # every reader rejection is a fast-path None
    for bad in (
        {"version": 1, "partitions": [{"topic": 1}]},
        {"version": 1, "partitions": [{"weight": True}]},
        {"version": 1, "partitions": [{"replicas": [True]}]},
        {"version": 1, "partitions": [{"partition": "x"}]},
        {"version": 2, "partitions": [{}]},
        {"version": True, "partitions": [{}]},
        {"version": 1, "partitions": "nope"},
        {"version": 1},
        {"version": 1, "partitions": []},
        [1, 2],
    ):
        text = json.dumps(bad)
        assert sstate.client_state(text, True, []) is None, bad
        with pytest.raises(Exception):
            pl2 = get_partition_list_from_reader(text, True, [])
            assert len(pl2) == 0  # unreachable: reader raises first


def test_prediction_matches_next_client_read(tmp_path):
    """The core fast-path invariant: snapshot + tap(change) + finish
    predicts exactly the digest of the outer loop's next read (the
    same input with only the moved row's replicas changed)."""
    text = open(FIXTURE).read()
    pl = get_partition_list_from_reader(text, True, [])
    sess = ClusterSession("t", "")
    sess.snapshot_from(pl)
    part = pl.partitions[3]
    part.replicas[:] = [2, 3]
    sess.change(part)
    sess.finish(0)
    state = _fixture_state()
    for row in state["partitions"]:
        if row["topic"] == part.topic and row["partition"] == part.partition:
            row["replicas"] = [2, 3]
    st = sstate.client_state(json.dumps(state), True, [])
    assert st is not None and st.digest == sess.digest


def test_failed_request_poisons_prediction():
    pl = get_partition_list_from_reader(open(FIXTURE).read(), True, [])
    sess = ClusterSession("t", "")
    sess.snapshot_from(pl)
    sess.finish(3)
    assert sess.digest is None


def test_untracked_mutation_poisons_prediction():
    from kafkabalancer_tpu.models import Partition

    pl = get_partition_list_from_reader(open(FIXTURE).read(), True, [])
    sess = ClusterSession("t", "")
    sess.snapshot_from(pl)
    sess.change(Partition(topic="ghost", partition=9, replicas=[1]))
    assert sess.digest is None


def test_universe_dirty_on_vacated_broker():
    """A move draining a broker's last replica flips universe_dirty —
    the resident settled list would keep a stale defaulted allowed
    set, so the session must rebuild even on a digest match."""
    text = json.dumps({"version": 1, "partitions": [
        {"topic": "a", "partition": 0, "replicas": [1, 2]},
        {"topic": "a", "partition": 1, "replicas": [1, 3]},
    ]})
    pl = get_partition_list_from_reader(text, True, [])
    sess = ClusterSession("t", "")
    sess.snapshot_from(pl)
    assert not sess.universe_dirty
    part = pl.partitions[1]
    part.replicas[:] = [1, 2]  # broker 3 vacated
    sess.change(part)
    assert sess.universe_dirty
    rebuilt = sess.rebuild_pl()
    assert not sess.universe_dirty
    assert [p.replicas for p in rebuilt.iter_partitions()] == [[1, 2], [1, 2]]


def test_pack_unpack_rows_roundtrip():
    rows = [
        (0, ("topic-α", 3, [1, 2, 9999999999], 1.5, 3, None, 0)),
        (7, ("t", 0, [], 0.0, 0, [4, 5], 2)),
    ]
    blob = sstate.pack_rows(rows)
    assert sstate.unpack_rows(blob) == rows
    with pytest.raises(ValueError):
        sstate.unpack_rows(blob[:-3])


def test_hash_table_and_diff():
    hashes = [b"12345678", b"abcdefgh", b"ABCDEFGH"]
    blob = sstate.pack_hash_table(hashes)
    assert sstate.unpack_hash_table(blob) == hashes
    with pytest.raises(ValueError):
        sstate.unpack_hash_table(blob[:-1])
    theirs = [b"12345678", b"xxxxxxxx", b"ABCDEFGH"]
    assert sstate.diff_rows(hashes, theirs) == [1]
    assert sstate.diff_rows(hashes, theirs[:2]) is None  # row count drift


def test_flags_signature_excludes_output_flags():
    a = ["-no-daemon=true", "-fused=true", "-max-reassign=4",
         "-metrics-json=/x", "-stats=true", "-full-output=true"]
    b = ["-no-daemon=true", "-fused=true", "-max-reassign=4"]
    assert flags_signature(a) == flags_signature(b)
    assert flags_signature(a) != flags_signature(b + ["-solver=tpu"])


# --- protocol v2 frames ----------------------------------------------------


def test_frame2_roundtrip_and_caps():
    a, b = socket_mod.socketpair()
    try:
        protocol.write_frame2(a, {"v": 2, "op": "x"}, b"\x00\x01raw")
        got = protocol.read_frame2(b)
        assert got == ({"v": 2, "op": "x"}, b"\x00\x01raw")
        protocol.write_frame2(a, {"v": 2})
        assert protocol.read_frame2(b) == ({"v": 2}, b"")
        a.close()
        assert protocol.read_frame2(b) is None  # clean EOF
    finally:
        b.close()
    with pytest.raises(ValueError):
        protocol.write_frame2(
            None, {"v": 2}, b"x" * (protocol.MAX_FRAME_BYTES + 1)
        )


# --- SessionStore: LRU, idle expiry, release -------------------------------


def test_store_lru_cap_under_thousands():
    store = SessionStore(cap=32, idle_s=0)
    for i in range(2000):
        s = ClusterSession(f"tenant-{i}", "")
        s.approx_bytes = 100
        store.put((f"tenant-{i}", ""), s)
    st = store.stats()
    assert st["count"] == 32
    assert st["evicted_lru"] == 2000 - 32
    assert st["registered"] == 2000
    assert st["bytes"] == 32 * 100
    # most-recent survivors
    assert store.get(("tenant-1999", "")) is not None
    assert store.get(("tenant-0", "")) is None


def test_store_idle_expiry_and_in_use_protection():
    store = SessionStore(cap=10, idle_s=5.0)
    s1 = ClusterSession("a", "")
    s2 = ClusterSession("b", "")
    store.put(("a", ""), s1)
    store.put(("b", ""), s2)
    got, busy = store.checkout(("a", ""))
    assert got is s1 and not busy
    # second checkout of the same session reports busy, not a block
    none, busy2 = store.checkout(("a", ""))
    assert none is None and busy2
    now = time.monotonic() + 60
    assert store.sweep(now) == 1  # only the idle one expires
    assert store.get(("b", "")) is None
    assert store.get(("a", "")) is s1  # in_use: protected
    store.checkin(s1)
    assert store.sweep(now) == 1
    assert store.stats()["expired_idle"] == 2


def test_store_release_by_tenant():
    store = SessionStore(cap=10, idle_s=0)
    store.put(("a", "sig1"), ClusterSession("a", "sig1"))
    store.put(("a", "sig2"), ClusterSession("a", "sig2"))
    store.put(("b", ""), ClusterSession("b", ""))
    assert store.release("a") == 2
    assert store.stats()["count"] == 1 and store.stats()["released"] == 2


# --- trusted-delta row cache ----------------------------------------------


def test_trusted_delta_patch_matches_full_encode():
    import numpy as np

    from kafkabalancer_tpu.models import default_rebalance_config
    # NOTE: ops/__init__ shadows the tensorize SUBMODULE with the
    # tensorize function; import the seam directly from the module
    from kafkabalancer_tpu.ops.tensorize import (
        set_thread_row_cache,
        tensorize,
    )
    from kafkabalancer_tpu.serve.cache import TensorizeRowCache

    cfg = default_rebalance_config()
    pl = get_partition_list_from_reader(open(FIXTURE).read(), True, [])
    from kafkabalancer_tpu.balancer.steps import fill_defaults

    fill_defaults(pl, cfg)
    cache = TensorizeRowCache()
    cache.enable_trusted_deltas()
    set_thread_row_cache(cache)
    try:
        tensorize(pl, cfg)  # prime
        pl.partitions[2].replicas[0] = 3
        cache.mark_changed(2)
        dp = tensorize(pl, cfg)  # trusted patch: no key scan
        assert cache.stats()["hits"] == 1
    finally:
        set_thread_row_cache(None)
    fresh = tensorize(pl, cfg)
    for field in ("weights", "replicas", "nrep_cur", "nrep_tgt", "ncons",
                  "allowed", "member", "pvalid", "bvalid", "topic_id"):
        assert np.array_equal(getattr(dp, field), getattr(fresh, field)), field


# --- end to end through the daemon ----------------------------------------


@pytest.mark.parametrize("mode_args", [
    ["-solver=greedy"],
    ["-solver=tpu"],
    ["-solver=beam"],
    ["-fused", "-fused-batch=2"],
], ids=["greedy", "tpu", "beam", "fused"])
def test_outer_loop_delta_parity_per_solver(daemon, sock_dir, mode_args):
    """Three outer-loop steps per solver mode: every served step —
    register, then digest-matched delta requests — is byte-identical
    (stdout + rc, stderr modulo timestamps) to a fresh ``-no-daemon``
    run on the same state, and the emitted moves round-trip through
    the simulated cluster."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    args = ["-input-json", f"-input={input_path}", "-max-reassign=2"]
    args += mode_args
    for step in range(3):
        with open(input_path, "w") as f:
            json.dump(state, f)
        want_rv, want_out, want_err = run_cli(args + ["-no-daemon"])
        got_rv, got_out, got_err = run_cli(args + [f"-serve-socket={sock}"])
        assert (got_rv, got_out) == (want_rv, want_out), f"step {step}"
        assert strip_ts(got_err) == strip_ts(want_err), f"step {step}"
        _apply_plan(state, want_out)
    st = d.sessions.stats()
    assert st["delta_hits"] >= 1, st
    assert st["bytes"] > 0


def test_outer_loop_steady_state_hits_delta_path(daemon, sock_dir):
    """The steady state is delta hits: after register, every subsequent
    predicted request plans with ZERO state shipped (delta_hits grows
    per step), and the served attribution gauges carry the session
    block."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    metrics = os.path.join(sock_dir, "m.json")
    args = ["-input-json", f"-input={input_path}", "-solver=tpu",
            "-max-reassign=1", f"-serve-socket={sock}",
            f"-metrics-json={metrics}"]
    hits = []
    for _step in range(4):
        with open(input_path, "w") as f:
            json.dump(state, f)
        rv, out, _ = run_cli(args)
        assert rv == 0
        hits.append(d.sessions.stats()["delta_hits"])
        _apply_plan(state, out)
    assert hits[-1] >= 2, hits
    payload = json.load(open(metrics))
    assert payload["gauges"]["served"] is True
    assert payload["gauges"]["serve.delta_hit"] is True
    assert payload["gauges"]["serve.sessions"] >= 1.0
    assert payload["gauges"]["serve.session_bytes"] > 0


def test_external_drift_resyncs_rows_byte_identical(daemon, sock_dir):
    """Cluster drift the daemon could not predict (an out-of-band
    replica change) mismatches the digest; the row-level resync ships
    only the drifted rows and the plan stays byte-identical."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    args = ["-input-json", f"-input={input_path}", "-solver=tpu",
            "-max-reassign=1"]
    with open(input_path, "w") as f:
        json.dump(state, f)
    rv, out, _ = run_cli(args + [f"-serve-socket={sock}"])
    assert rv == 0
    _apply_plan(state, out)
    # out-of-band drift: mutate a row the plan did not touch
    state["partitions"][0]["replicas"] = [2, 3]
    with open(input_path, "w") as f:
        json.dump(state, f)
    want_rv, want_out, want_err = run_cli(args + ["-no-daemon"])
    got_rv, got_out, got_err = run_cli(args + [f"-serve-socket={sock}"])
    assert (got_rv, got_out) == (want_rv, want_out)
    assert strip_ts(got_err) == strip_ts(want_err)
    assert d.sessions.stats()["resyncs_rows"] >= 1


def test_structural_drift_full_resync_byte_identical(daemon, sock_dir):
    """A row-count change (new partition appears) cannot row-patch;
    the client re-registers the full state and parity holds."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    args = ["-input-json", f"-input={input_path}", "-solver=greedy",
            "-max-reassign=1"]
    with open(input_path, "w") as f:
        json.dump(state, f)
    rv, _out, _ = run_cli(args + [f"-serve-socket={sock}"])
    assert rv == 0
    registered_before = d.sessions.stats()["registered"]
    state["partitions"].append(
        {"topic": "fresh", "partition": 0, "replicas": [1, 2]}
    )
    with open(input_path, "w") as f:
        json.dump(state, f)
    want_rv, want_out, want_err = run_cli(args + ["-no-daemon"])
    got_rv, got_out, got_err = run_cli(args + [f"-serve-socket={sock}"])
    assert (got_rv, got_out) == (want_rv, want_out)
    assert strip_ts(got_err) == strip_ts(want_err)
    assert d.sessions.stats()["registered"] == registered_before + 1


def test_complete_partition_probe_move_never_wrong(daemon, sock_dir):
    """The complete-partition probe move is applied to the live list
    but not emitted — the cluster never sees it. The session reverts
    it post-run (serve/sessions.py apply_unemitted_reverts), so the
    DEFAULT flag set still hits the delta fast path, byte-identically
    (the aliasing subtlety: the revert must not change the emitted
    bytes, which can alias the probe partition)."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    args = ["-input-json", f"-input={input_path}", "-solver=greedy",
            "-max-reassign=2", "-complete-partition"]
    for step in range(4):
        with open(input_path, "w") as f:
            json.dump(state, f)
        want_rv, want_out, want_err = run_cli(args + ["-no-daemon"])
        got_rv, got_out, got_err = run_cli(args + [f"-serve-socket={sock}"])
        assert (got_rv, got_out) == (want_rv, want_out), f"step {step}"
        assert strip_ts(got_err) == strip_ts(want_err), f"step {step}"
        _apply_plan(state, want_out)
    # the probe-move revert keeps the prediction live: steps after the
    # register hit the delta path despite the unemitted applies
    assert d.sessions.stats()["delta_hits"] >= 1


def test_serve_no_session_disables(daemon, sock_dir):
    sock, d = daemon
    args = ["-input-json", f"-input={FIXTURE}", "-serve-no-session",
            f"-serve-socket={sock}"]
    want_rv, want_out, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-no-daemon"]
    )
    rv, out, _ = run_cli(args)
    assert (rv, out) == (want_rv, want_out)
    assert d.sessions.stats()["count"] == 0


def test_explicit_session_name_and_release(daemon):
    sock, d = daemon
    args = ["-input-json", f"-input={FIXTURE}", "-serve-session=my-fleet",
            f"-serve-socket={sock}"]
    rv, _out, _ = run_cli(args)
    assert rv == 0
    assert d.sessions.get(
        ("my-fleet", flags_signature(["-input-json=true"]))
    ) is not None
    released = sclient.release_session(sock, "my-fleet")
    assert released == 1
    assert d.sessions.stats()["count"] == 0


def test_v1_client_against_v2_daemon_byte_identical(daemon):
    """Handshake compatibility pin: a raw v1-protocol conversation
    (no max_v in hello, JSON plan frame) gets the exact plan a
    ``-no-daemon`` run produces — the daemon only switches framing for
    clients that advertised v2."""
    sock, _d = daemon
    want_rv, want_out, want_err = run_cli(
        ["-input-json", "-no-daemon"], stdin=open(FIXTURE).read()
    )
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.settimeout(60)
    try:
        s.connect(sock)
        protocol.write_frame(s, {"v": 1, "op": "hello"})
        hello = protocol.read_frame(s)
        assert hello["ok"] and hello["v"] == 1
        assert hello["max_v"] >= 2  # advertised, not imposed
        protocol.write_frame(s, {
            "v": 1, "op": "plan",
            "argv": ["-input-json=true", "-no-daemon=true"],
            "stdin": open(FIXTURE).read(),
        })
        resp = protocol.read_frame(s)
    finally:
        s.close()
    assert resp["ok"] and resp["v"] == 1
    assert resp["rc"] == want_rv
    assert resp["stdout"] == want_out
    assert strip_ts(resp["stderr"]) == strip_ts(want_err)


def test_v1_library_client_still_forwards(daemon, monkeypatch):
    """An OLD client build (one that never negotiates v2) keeps
    forwarding through the new daemon byte-identically."""
    sock, d = daemon

    def old_forward(path, argv, stdin_text, **_kw):
        s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
        s.settimeout(60)
        try:
            s.connect(path)
            protocol.write_frame(s, {"v": 1, "op": "hello"})
            hello = protocol.read_frame(s)
            if not hello or not hello.get("ok"):
                return None
            req = {"v": 1, "op": "plan", "argv": argv}
            if stdin_text is not None:
                req["stdin"] = stdin_text
            protocol.write_frame(s, req)
            resp = protocol.read_frame(s)
            return sclient.ServedResult(
                resp["rc"], resp["stdout"], resp["stderr"]
            )
        finally:
            s.close()

    monkeypatch.setattr(sclient, "forward_plan", old_forward)
    want_rv, want_out, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-no-daemon"]
    )
    rv, out, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
    )
    assert (rv, out) == (want_rv, want_out)
    assert d.sessions.stats()["count"] == 0  # v1 path: no session


# --- fallback attribution --------------------------------------------------


def test_client_fallback_counter_daemon_down(sock_dir):
    """A dead socket file: the invocation plans in-process (stderr
    silent, parity preserved elsewhere) and the fallback REASON lands
    as a counter in its own metrics export."""
    stale = os.path.join(sock_dir, "stale.sock")
    with open(stale, "w") as f:
        f.write("not a socket")
    metrics = os.path.join(sock_dir, "m.json")
    rv, _out, _err = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={stale}",
         f"-metrics-json={metrics}"]
    )
    assert rv == 0
    payload = json.load(open(metrics))
    assert payload["counters"].get("serve.fallbacks.daemon_down") == 1


def test_daemon_fallback_counters_in_scrape(daemon):
    """Daemon-observed fallback reasons ride the stats scrape and the
    Prometheus rendering."""
    sock, d = daemon
    # provoke a version-mismatch refusal
    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.settimeout(10)
    try:
        s.connect(sock)
        protocol.write_frame(s, {"v": 99, "op": "hello"})
        resp = protocol.read_frame(s)
        assert resp["ok"] is False
    finally:
        s.close()
    doc = sclient.fetch_stats(sock)
    assert doc["fallbacks"].get("version_mismatch", 0) >= 1
    from kafkabalancer_tpu.obs.export import (
        render_prometheus,
        render_serve_stats,
    )

    prom = render_prometheus(doc)
    assert 'kafkabalancer_tpu_serve_fallbacks{reason="version_mismatch"}' \
        in prom
    assert "kafkabalancer_tpu_sessions_count" in prom
    human = render_serve_stats(doc)
    assert "sessions:" in human and "fallbacks:" in human


def test_session_stats_in_hello_and_scrape(daemon):
    sock, d = daemon
    rv, _out, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", f"-serve-socket={sock}"]
    )
    assert rv == 0
    hello = sclient.daemon_alive(sock)
    doc = sclient.fetch_stats(sock)
    for scrape in (hello, doc):
        assert scrape["sessions"]["count"] == 1
        assert scrape["sessions"]["bytes"] > 0
        assert scrape["sessions"]["registered"] == 1


# --- the warm spill tier (serve/spill.py, serve/state.py spill codec) ------


def _fields(topic="t", partition=0, replicas=(1, 2), weight=1.0,
            nrep=2, brokers=None, ncons=0):
    return (topic, partition, list(replicas), weight, nrep,
            None if brokers is None else list(brokers), ncons)


def test_spill_record_roundtrip_edge_rows():
    """The spill codec's edge rows: unicode topics, empty and
    MAX-length replica lists (u16 bound), absent-vs-null broker
    allowlists — every field byte-exact through pack/unpack."""
    rows = [
        _fields(topic="tøpic-ünicode-⚡", partition=3),
        _fields(topic="empty-replicas", replicas=(), nrep=0),
        _fields(topic="max-replicas", replicas=tuple(range(65535))),
        _fields(topic="brokers-none", brokers=None),
        _fields(topic="brokers-empty", brokers=()),   # [] != None
        _fields(topic="brokers-set", brokers=(5, 6, 7)),
        _fields(topic="negative-weight", weight=-2.5, partition=2**40),
    ]
    rec = sstate.pack_spill_record(
        {"tenant": "ten", "sig": "sig", "digest": "d", "version": 1},
        rows,
    )
    hdr, back = sstate.unpack_spill_record(rec)
    assert back == rows
    assert back[3][5] is None and back[4][5] == []  # absent vs null
    assert hdr["rows"] == len(rows)
    assert hdr["platform"] == sstate.spill_platform()


@pytest.mark.parametrize("where", ["head", "header", "blob", "checksum"])
def test_spill_record_truncation_raises_cleanly(where):
    """A truncated record NEVER partially restores: every cut point
    raises SpillCorrupt (the store turns it into a counted cold miss,
    so a torn write can produce a slow answer, never a wrong one)."""
    rec = sstate.pack_spill_record(
        {"tenant": "t", "sig": "s", "digest": "d", "version": 1},
        [_fields(partition=i) for i in range(8)],
    )
    cut = {
        "head": 3,
        "header": 20,
        "blob": len(rec) // 2,
        "checksum": len(rec) - 7,
    }[where]
    with pytest.raises(sstate.SpillCorrupt):
        sstate.unpack_spill_record(rec[:cut])


def test_spill_record_bit_flips_raise_cleanly():
    """Any single flipped bit — header, payload or checksum region —
    fails the validated read wholesale."""
    rec = sstate.pack_spill_record(
        {"tenant": "t", "sig": "s", "digest": "d", "version": 1},
        [_fields(partition=i) for i in range(8)],
    )
    for pos in (6, 15, len(rec) // 2, len(rec) - 40, len(rec) - 1):
        bad = rec[:pos] + bytes([rec[pos] ^ 0x10]) + rec[pos + 1:]
        with pytest.raises(sstate.SpillCorrupt):
            sstate.unpack_spill_record(bad)


def test_spill_record_version_and_platform_gates():
    """A format-version-skewed or foreign-platform record is refused
    BEFORE any row decode — restores never reason about foreign
    encodings."""
    rec = sstate.pack_spill_record(
        {"tenant": "t", "sig": "s", "digest": "d", "version": 1},
        [_fields()],
    )
    # format version lives in bytes 4..8 (">4sII" after the magic)
    skewed = rec[:4] + (99).to_bytes(4, "big") + rec[8:]
    with pytest.raises(sstate.SpillCorrupt):
        sstate.unpack_spill_record(skewed)
    with pytest.raises(sstate.SpillCorrupt):
        sstate.unpack_spill_record(b"NOPE" + rec[4:])
    # a foreign-platform fingerprint: rebuild the record with a bad
    # platform but a VALID checksum — the platform gate must still
    # refuse it (policy, not just integrity)
    import unittest.mock as mock

    with mock.patch.object(
        sstate, "spill_platform", return_value="big:0.0.0-foreign"
    ):
        foreign = sstate.pack_spill_record(
            {"tenant": "t", "sig": "s", "digest": "d", "version": 1},
            [_fields()],
        )
    with pytest.raises(sstate.SpillCorrupt) as ei:
        sstate.unpack_spill_record(foreign)
    assert "foreign-platform" in str(ei.value)


def _mini_session(tenant="ten", sig="sig", n=4):
    from kafkabalancer_tpu.models import Partition
    from kafkabalancer_tpu.models.partition import PartitionList

    sess = ClusterSession(tenant, sig)
    pl = PartitionList(version=1, partitions=[
        Partition(
            topic="t", partition=i, replicas=[1, 2], weight=1.0,
            num_replicas=2, brokers=None, num_consumers=0,
        )
        for i in range(n)
    ])
    sess.snapshot_from(pl)
    return sess


def test_spill_store_demotion_and_restore_roundtrip(tmp_path):
    """SessionStore eviction DEMOTES to the warm tier instead of
    discarding, and session_from_rows rebuilds an equivalent session
    (same digest, same raw rows) from the spilled record."""
    from kafkabalancer_tpu.serve.spill import SpillStore
    from kafkabalancer_tpu.serve.sessions import session_from_rows

    spill = SpillStore(str(tmp_path / "spill"), cap_mb=4)
    assert spill.open() is None
    store = SessionStore(cap=1, spill=spill)
    s1 = _mini_session(tenant="a")
    s2 = _mini_session(tenant="b")
    store.put(("a", "sig"), s1)
    store.put(("b", "sig"), s2)  # evicts a past cap=1 -> spills it
    st = spill.stats()
    assert st["spills"] == 1 and st["warm_entries"] == 1
    assert store.stats()["evicted_lru"] == 1
    loaded = spill.load(("a", "sig"))
    assert loaded is not None
    hdr, rows = loaded
    restored = session_from_rows("a", "sig", int(hdr["version"]), rows)
    assert restored.digest == s1.digest
    assert [p.replicas for p in restored.raw] == [
        p.replicas for p in s1.raw
    ]
    st = spill.stats()
    assert st["restores"] == 1 and st["warm_entries"] == 0
    # conservation identity
    assert st["spills"] + st["adopted"] == (
        st["restores"] + st["corrupt_drops"] + st["evictions"]
        + st["warm_entries"]
    )
    spill.close()


def test_spill_store_poisoned_session_not_spilled(tmp_path):
    """A session whose prediction is poisoned (digest None) must never
    be persisted — its raw shadow is untrustworthy."""
    from kafkabalancer_tpu.serve.spill import SpillStore

    spill = SpillStore(str(tmp_path / "spill"))
    assert spill.open() is None
    sess = _mini_session()
    sess.digest = None
    assert spill.spill(("ten", "sig"), sess) is False
    assert spill.stats()["spills"] == 0
    assert spill.stats()["write_failures"] == 0  # a skip, not a failure
    spill.close()


def test_spill_store_byte_budget_lru_sweep(tmp_path):
    """The warm tier is byte-bounded: past -serve-warm-cap-mb the
    least-recently-spilled records are swept (counted as evictions,
    identity preserved)."""
    from kafkabalancer_tpu.serve.spill import SpillStore

    spill = SpillStore(str(tmp_path / "spill"), cap_mb=0.002)  # ~2KB
    assert spill.open() is None
    for i in range(8):
        spill.spill((f"t{i}", "sig"), _mini_session(tenant=f"t{i}", n=8))
    st = spill.stats()
    assert st["warm_bytes"] <= st["cap_bytes"]
    assert st["evictions"] >= 1
    assert st["spills"] == 8
    assert st["spills"] + st["adopted"] == (
        st["restores"] + st["corrupt_drops"] + st["evictions"]
        + st["warm_entries"]
    )
    # the survivors are the most recently spilled
    assert spill.load(("t7", "sig")) is not None
    assert spill.load(("t0", "sig")) is None
    spill.close()


def test_spill_store_overwrite_counts_replaced_as_eviction(tmp_path):
    """The continuous spill overwrites a session's record as its state
    moves; each replaced record counts as an eviction so the
    conservation identity stays exact — and a digest-unchanged
    re-spill is skipped entirely."""
    from kafkabalancer_tpu.serve.spill import SpillStore

    spill = SpillStore(str(tmp_path / "spill"))
    assert spill.open() is None
    sess = _mini_session()
    key = ("ten", "sig")
    assert spill.spill(key, sess)
    assert spill.spill(key, sess)  # same digest: skipped
    assert spill.stats()["spills"] == 1
    sess.raw[0].replicas = [3, 4]
    sess._dirty.add(0)
    sess._refresh_digest()
    assert spill.spill(key, sess)  # new digest: overwrite
    st = spill.stats()
    assert st["spills"] == 2 and st["evictions"] == 1
    assert st["warm_entries"] == 1
    assert st["spills"] + st["adopted"] == (
        st["restores"] + st["corrupt_drops"] + st["evictions"]
        + st["warm_entries"]
    )
    spill.close()


def test_spill_dir_pidfile_rules(tmp_path):
    """The spill-dir claim follows the PR-12 takeover rules: a LIVE
    owner is refused, a dead owner's records are adopted and its
    *.tmp write orphans swept."""
    from kafkabalancer_tpu.serve.spill import PIDFILE_NAME, SpillStore

    d = str(tmp_path / "spill")
    first = SpillStore(d)
    assert first.open() is None
    first.spill(("ten", "sig"), _mini_session())
    # a LIVE owner (this very process counts as alive and, running
    # under pytest with the package imported, as daemon-like enough
    # via the cmdline fallback) — fake one with our own pid recorded
    # by `first`: a SECOND store may not share the dir
    import subprocess
    import sys as sys_mod

    child = subprocess.Popen(
        [sys_mod.executable, "-c",
         "import sys; sys.argv=['kafkabalancer','-serve'];"
         "print('up', flush=True);"
         "import time; time.sleep(60)"],
        stdout=subprocess.PIPE,
    )
    try:
        # wait until the child is past exec: before that its cmdline
        # still shows the forked pytest image, which is not
        # daemon-like, and the liveness probe below would race it
        assert child.stdout is not None and child.stdout.readline()
        with open(os.path.join(d, PIDFILE_NAME), "w") as f:
            f.write(f"{child.pid}\n")
        second = SpillStore(d)
        err = second.open()
        assert err is not None and "refusing" in err
    finally:
        child.kill()
        child.wait()
        if child.stdout is not None:
            child.stdout.close()
    # the owner is now DEAD: adoption proceeds, tmp orphans swept
    with open(os.path.join(d, "half-written.kbsp.tmp"), "wb") as f:
        f.write(b"torn")
    third = SpillStore(d)
    assert third.open() is None
    st = third.stats()
    assert st["adopted"] == 1 and st["warm_entries"] == 1
    assert not os.path.exists(os.path.join(d, "half-written.kbsp.tmp"))
    assert third.load(("ten", "sig")) is not None
    third.close()


def test_spill_store_corrupt_record_is_counted_cold_miss(tmp_path):
    """A bit-flipped record on disk: load() prunes + counts it and
    answers None — the caller's cold path, never a wrong restore."""
    from kafkabalancer_tpu.serve.spill import SpillStore, record_name

    spill = SpillStore(str(tmp_path / "spill"))
    assert spill.open() is None
    key = ("ten", "sig")
    spill.spill(key, _mini_session())
    path = os.path.join(spill.dir, record_name(key))
    buf = bytearray(open(path, "rb").read())
    buf[len(buf) // 2] ^= 0x20
    with open(path, "wb") as f:
        f.write(bytes(buf))
    assert spill.load(key) is None
    st = spill.stats()
    assert st["corrupt_drops"] == 1 and st["restores"] == 0
    assert not os.path.exists(path)  # pruned
    assert st["spills"] + st["adopted"] == (
        st["restores"] + st["corrupt_drops"] + st["evictions"]
        + st["warm_entries"]
    )
    spill.close()


def test_stats_by_tenant_keeps_demoted_warm_attribution(tmp_path):
    """The demotion-accounting fix: a tenant whose only session was
    demoted to warm still appears in stats_by_tenant() with its warm
    byte attribution (the -serve-stats table's hot/warm column)
    instead of silently vanishing."""
    from kafkabalancer_tpu.obs.export import _render_tenant_table
    from kafkabalancer_tpu.serve.spill import SpillStore

    spill = SpillStore(str(tmp_path / "spill"))
    assert spill.open() is None
    store = SessionStore(cap=1, spill=spill)
    store.put(("cold-tenant", "sig"), _mini_session(tenant="cold-tenant"))
    store.put(("hot-tenant", "sig"), _mini_session(tenant="hot-tenant"))
    by = store.stats_by_tenant()
    assert by["hot-tenant"]["sessions"] == 1
    assert by["hot-tenant"]["warm_sessions"] == 0
    # fully demoted, still attributed:
    assert by["cold-tenant"]["sessions"] == 0
    assert by["cold-tenant"]["warm_sessions"] == 1
    assert by["cold-tenant"]["warm_bytes"] > 0
    # and the human table renders a warm column for it
    table = "\n".join(_render_tenant_table({
        "cap": 32, "demoted": 0,
        "top": {
            t: {
                "requests": 1, "request_s": None, "delta_hits": 0,
                "session_bytes": e["bytes"],
                "warm_sessions": e["warm_sessions"],
                "warm_bytes": e["warm_bytes"],
            }
            for t, e in by.items()
        },
        "other": None,
    }))
    assert "cold-tenant" in table and "warm" in table
    spill.close()


def test_release_drops_warm_records_too(tmp_path):
    """An explicit release forgets BOTH tiers — a released tenant must
    not be silently restorable from disk. (In-store check; the daemon
    op wiring is covered by the durability e2e below.)"""
    from kafkabalancer_tpu.serve.spill import SpillStore

    spill = SpillStore(str(tmp_path / "spill"))
    assert spill.open() is None
    spill.spill(("ten", "sig-a"), _mini_session(sig="sig-a"))
    spill.spill(("ten", "sig-b"), _mini_session(sig="sig-b"))
    spill.spill(("other", "sig"), _mini_session(tenant="other"))
    assert spill.release("ten") == 2
    st = spill.stats()
    assert st["warm_entries"] == 1 and st["evictions"] == 2
    assert spill.load(("ten", "sig-a")) is None
    assert spill.load(("other", "sig")) is not None
    spill.close()


@pytest.fixture
def durable_daemon(sock_dir):
    """A daemon with the warm tier enabled, restartable in-thread on
    the same socket + spill dir."""
    sock = os.path.join(sock_dir, "kb.sock")
    spill_dir = os.path.join(sock_dir, "spill")
    procs = []

    def start(faults_spec=""):
        d = Daemon(
            sock, idle_timeout=60.0, warm=False, log=lambda _m: None,
            spill_dir=spill_dir, warm_cap_mb=16,
            faults_spec=faults_spec,
        )
        rc_box = []
        t = threading.Thread(
            target=lambda: rc_box.append(d.serve_forever()), daemon=True
        )
        t.start()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if sclient.daemon_alive(sock) is not None:
                break
            time.sleep(0.02)
        else:
            pytest.fail("durable daemon never became ready")
        procs.append((d, t, rc_box))
        return d

    def stop():
        sclient.request_shutdown(sock)
        d, t, rc_box = procs[-1]
        t.join(15)
        assert rc_box == [0], rc_box

    yield sock, spill_dir, start, stop
    try:
        if sclient.daemon_alive(sock) is not None:
            stop()
    except Exception:
        pass


def _apply_plan_text(state_text, plan_text):
    state = json.loads(state_text)
    plan = json.loads(plan_text)
    for entry in plan.get("partitions") or []:
        for row in state["partitions"]:
            if (row["topic"] == entry["topic"]
                    and row["partition"] == entry["partition"]):
                row["replicas"] = list(entry["replicas"])
                break
    return json.dumps(state)


def test_durability_e2e_shutdown_flush_and_restore(durable_daemon):
    """The durability acceptance, in-thread: register + delta, clean
    shutdown (flush), restart on the same spill dir, and the next
    digest-matching request restores from spill — serve.restore_hit
    attributed, plan bytes identical to -no-daemon, conservation
    identity exact, warm tenant attribution present."""
    sock, _spill_dir, start, stop = durable_daemon
    start()
    state = open(FIXTURE).read()
    args = ["-input-json", "-serve-session=dur-ten",
            f"-serve-socket={sock}", "-max-reassign=1"]
    rv, out1, _ = run_cli(args, stdin=state)
    assert rv == 0
    state = _apply_plan_text(state, out1)
    stop()   # shutdown flush
    start()  # adopts the flushed record
    want_rv, want_out, _ = run_cli(
        ["-input-json", "-max-reassign=1", "-no-daemon"], stdin=state
    )
    import tempfile as tempfile_mod

    with tempfile_mod.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as mf:
        metrics_path = mf.name
    rv, out2, _ = run_cli(args + [f"-metrics-json={metrics_path}"],
                          stdin=state)
    assert rv == 0
    assert (rv, out2) == (want_rv, want_out)
    payload = json.loads(open(metrics_path).read())
    os.unlink(metrics_path)
    assert payload["gauges"].get("serve.restore_hit") is True
    doc = sclient.fetch_stats(sock)
    pg = doc["paging"]
    assert pg["enabled"] is True
    assert pg["restore_hits"] == 1 and pg["adopted"] == 1
    assert pg["spills"] + pg["adopted"] == (
        pg["restores"] + pg["corrupt_drops"] + pg["evictions"]
        + pg["warm_entries"]
    )
    ten = doc["tenants"]["top"]["dur-ten"]
    assert ten["restores"] == 1
    # the restored session is hot again: the NEXT step is a plain
    # delta hit
    state = _apply_plan_text(state, out2)
    want_rv, want_out, _ = run_cli(
        ["-input-json", "-max-reassign=1", "-no-daemon"], stdin=state
    )
    rv, out3, _ = run_cli(args, stdin=state)
    assert (rv, out3) == (want_rv, want_out)
    assert sclient.fetch_stats(sock)["sessions"]["delta_hits"] >= 1


def test_durability_e2e_corrupt_spill_is_cold_but_correct(durable_daemon):
    """spill_corrupt chaos: the record written for the session is
    bit-flipped on disk; after a restart the next request must be
    answered via a full re-register — byte-identical, corrupt_drops
    counted, restore_hits zero."""
    sock, _spill_dir, start, stop = durable_daemon
    start(faults_spec="spill_corrupt@1")
    state = open(FIXTURE).read()
    args = ["-input-json", "-serve-session=dur-ten",
            f"-serve-socket={sock}", "-max-reassign=1"]
    rv, out1, _ = run_cli(args, stdin=state)
    assert rv == 0
    state = _apply_plan_text(state, out1)
    stop()   # flush skips (digest unchanged since the corrupt write)
    start()
    want_rv, want_out, _ = run_cli(
        ["-input-json", "-max-reassign=1", "-no-daemon"], stdin=state
    )
    rv, out2, _ = run_cli(args, stdin=state)
    assert (rv, out2) == (want_rv, want_out)
    doc = sclient.fetch_stats(sock)
    pg = doc["paging"]
    assert pg["corrupt_drops"] == 1
    assert pg["restore_hits"] == 0 and pg["restores"] == 0
    assert doc["fallbacks"].get("session_absent", 0) >= 1
    assert doc["sessions"]["registered"] >= 1  # the re-register
    assert pg["spills"] + pg["adopted"] == (
        pg["restores"] + pg["corrupt_drops"] + pg["evictions"]
        + pg["warm_entries"]
    )


def test_durability_e2e_spill_write_fail_never_wrong(durable_daemon):
    """spill_write_fail chaos: the continuous spill dies like a full
    disk — the answer is still served and byte-correct, the failure is
    counted, and the restart simply takes the cold path."""
    sock, _spill_dir, start, stop = durable_daemon
    start(faults_spec="spill_write_fail@1,2,3,4")
    state = open(FIXTURE).read()
    args = ["-input-json", "-serve-session=dur-ten",
            f"-serve-socket={sock}", "-max-reassign=1"]
    want_rv, want_out, _ = run_cli(
        ["-input-json", "-max-reassign=1", "-no-daemon"], stdin=state
    )
    rv, out1, _ = run_cli(args, stdin=state)
    assert (rv, out1) == (want_rv, want_out)
    doc = sclient.fetch_stats(sock)
    pg = doc["paging"]
    assert pg["write_failures"] >= 1
    assert pg["spills"] == 0 and pg["warm_entries"] == 0
    stop()
