"""TPU solver parity tests: the vectorized candidate scorer must produce
byte-identical plans to the greedy oracle (which is itself pinned against
the Go reference by the golden table tests).

Covers the full golden table under ``solver=tpu``, plus randomized
multi-move session parity across weighted/equal-weight instances, leader
rebalancing, restricted broker sets, and configured empty brokers —
equal-weight instances specifically exercise the host-exact tie-resolution
window (see solvers/tpu.py module docstring)."""

import copy
import random

import pytest

from helpers import random_partition_list
from test_balancer import CASES, P, wrap

from kafkabalancer_tpu.balancer import BalanceError, balance
from kafkabalancer_tpu.cli import apply_assignment
from kafkabalancer_tpu.models import default_rebalance_config
from kafkabalancer_tpu.solvers import tpu as tpu_solver


@pytest.fixture(autouse=True)
def _force_device_path(monkeypatch):
    # parity tests use small instances; force them onto the device path
    # (the production fallback would silently route them to the host scan)
    monkeypatch.setattr(tpu_solver, "MIN_DEVICE_CANDIDATES", 0)


def tpu_cfg(cfg):
    cfg = copy.deepcopy(cfg)
    cfg.solver = "tpu"
    return cfg


@pytest.mark.parametrize("idx", range(len(CASES)))
def test_golden_case_tpu(idx):
    pl_parts, expected, err, cfg_factory = CASES[idx]
    pl = wrap(pl_parts)
    cfg = tpu_cfg(cfg_factory() if cfg_factory else default_rebalance_config())

    if err is not None:
        with pytest.raises(BalanceError, match=err):
            balance(pl, cfg)
        return

    ppl = balance(pl, cfg)
    if expected is None:
        assert len(ppl) == 0
    else:
        assert ppl == wrap(expected)


def run_session(pl, cfg, max_moves):
    """Replicate the CLI main loop: balance + apply, collecting the plans."""
    out = []
    for _ in range(max_moves):
        ppl = balance(pl, cfg)
        if len(ppl) == 0:
            break
        for changed in ppl.partitions:
            live = apply_assignment(pl, changed)
            out.append((live.topic, live.partition, tuple(live.replicas)))
    return out


def assert_session_parity(pl, cfg, max_moves=6):
    pl_g, pl_t = copy.deepcopy(pl), copy.deepcopy(pl)
    cfg_g, cfg_t = copy.deepcopy(cfg), tpu_cfg(cfg)
    got_g = run_session(pl_g, cfg_g, max_moves)
    got_t = run_session(pl_t, cfg_t, max_moves)
    assert got_g == got_t
    assert pl_g == pl_t  # final assignments identical too


@pytest.mark.parametrize("weighted", [False, True])
@pytest.mark.parametrize("allow_leader", [False, True])
def test_random_session_parity(weighted, allow_leader):
    rng = random.Random(100 + weighted * 10 + allow_leader)
    for _ in range(6):
        pl = random_partition_list(
            rng,
            rng.randint(2, 25),
            rng.randint(2, 8),
            max_rf=3,
            weighted=weighted,
            with_consumers=True,
            restrict_brokers=True,
        )
        cfg = default_rebalance_config()
        cfg.allow_leader_rebalancing = allow_leader
        assert_session_parity(pl, cfg)


def test_session_parity_with_empty_configured_broker():
    """Configured brokers with no replicas are zero-filled valid targets
    (steps.go:150-155)."""
    rng = random.Random(42)
    for _ in range(4):
        pl = random_partition_list(rng, 12, 4, weighted=True)
        observed = sorted({b for p in pl.partitions for b in p.replicas})
        cfg = default_rebalance_config()
        cfg.brokers = observed + [max(observed) + 1, max(observed) + 2]
        assert_session_parity(pl, cfg)


def test_session_parity_equal_weights_many_ties():
    """Uniform weights produce massive candidate ties; the tie window must
    reproduce the oracle's accumulation-order tie-breaks exactly."""
    rng = random.Random(7)
    for _ in range(4):
        pl = random_partition_list(rng, 30, 6, weighted=False, max_rf=3)
        assert_session_parity(pl, default_rebalance_config(), max_moves=10)


def test_tpu_rejects_below_min_unbalance():
    pl = wrap(
        [
            P("a", 1, [1, 2], weight=1.0),
            P("a", 2, [2, 1], weight=1.0),
        ]
    )
    cfg = tpu_cfg(default_rebalance_config())
    assert len(balance(pl, cfg)) == 0


def test_tpu_single_partition_no_valid_target():
    # every broker already holds a replica → no candidate at all
    pl = wrap([P("a", 1, [1, 2, 3], weight=1.0, brokers=[1, 2, 3])])
    cfg = tpu_cfg(default_rebalance_config())
    assert len(balance(pl, cfg)) == 0


def test_accepted_moves_strictly_improve():
    """Property (SURVEY.md §4): every accepted reassignment lowers the
    unbalance by more than min_unbalance, for both move solvers."""
    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )

    def unbalance_of(pl):
        return get_unbalance_bl(get_bl(get_broker_load(pl)))

    rng = random.Random(5000)
    for solver in ("greedy", "tpu"):
        for _ in range(3):
            pl = random_partition_list(
                rng, rng.randint(6, 20), rng.randint(3, 7), weighted=True
            )
            cfg = default_rebalance_config()
            cfg.solver = solver
            for _move in range(6):
                ppl = balance(pl, cfg)
                if len(ppl) == 0:
                    break
                before = unbalance_of(pl)
                for changed in ppl.partitions:
                    apply_assignment(pl, changed)
                after = unbalance_of(pl)
                assert after < before - cfg.min_unbalance + 1e-12


def test_tiny_instance_host_fallback_still_identical(monkeypatch):
    """Tiny instances route to the host scan inside -solver=tpu (pinned by
    a spy — parity alone cannot distinguish the paths); outputs stay
    byte-identical by the contract."""
    monkeypatch.setattr(tpu_solver, "MIN_DEVICE_CANDIDATES", 20_000)
    calls = []
    orig = tpu_solver.greedy_move

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(tpu_solver, "greedy_move", spy)
    pl = wrap(
        [
            P("a", 1, [1, 2, 3], weight=1.0),
            P("a", 2, [2, 1, 4], weight=1.0),
            P("a", 3, [1, 2, 5], weight=1.0),
        ]
    )
    cfg = tpu_cfg(default_rebalance_config())
    ppl = balance(copy.deepcopy(pl), cfg)
    assert calls, "fallback did not fire"
    ppl_g = balance(copy.deepcopy(pl), default_rebalance_config())
    assert ppl == ppl_g


@pytest.mark.parametrize("leaders", [False, True])
@pytest.mark.parametrize("dtype", ["float32", "float64"])
def test_score_window_matches_score_moves_minima(leaders, dtype):
    """The packed window scorer's factored per-partition minima
    (su + min_slot A + min_target C — no [P, R, B] tensor on device) must
    equal the full candidate tensor's per-partition minima from
    ``score_moves`` in the same dtype, for both precision tiers."""
    import numpy as np

    from kafkabalancer_tpu.balancer.steps import fill_defaults
    from kafkabalancer_tpu.ops.tensorize import tensorize

    rng = random.Random(321)
    npdt = np.dtype(dtype)
    for case in range(4):
        pl = random_partition_list(
            rng, rng.randint(4, 20), rng.randint(3, 7),
            max_rf=3, weighted=True, with_consumers=True,
            restrict_brokers=(case % 2 == 1),
        )
        cfg = default_rebalance_config()
        fill_defaults(pl, cfg)
        dp = tensorize(pl, cfg)
        loads_map = tpu_solver._oracle_loads(pl, cfg)
        B = dp.bvalid.shape[0]
        loads = np.zeros(B, dtype=np.float64)
        for bid, load in loads_map.items():
            loads[dp.broker_index(bid)] = load

        ints, floats64, allowed_arg, all_allowed = (
            tpu_solver._pack_window_args(dp, loads, cfg)
        )
        out = np.asarray(
            tpu_solver._score_window_jit(
                ints, floats64.astype(npdt), allowed_arg,
                leaders=leaders, all_allowed=all_allowed,
            )
        )
        u_min, su, perpart = float(out[0]), float(out[1]), out[4:]

        ref = tpu_solver.score_moves(
            loads.astype(npdt), dp.replicas, dp.allowed, dp.member,
            dp.weights.astype(npdt), dp.nrep_cur, dp.nrep_tgt, dp.pvalid,
            dp.bvalid, npdt.type(dp.nb),
            int(cfg.min_replicas_for_rebalancing),
            leaders=leaders, tie_k=1,
        )
        ref_umin, ref_su, ref_pp = (
            float(ref[0]), float(ref[2]), np.asarray(ref[4])
        )
        tol = 1e-5 if dtype == "float32" else 1e-12
        scale = max(1.0, abs(su))
        assert abs(su - ref_su) <= tol * scale
        if np.isfinite(ref_umin) or np.isfinite(u_min):
            assert abs(u_min - ref_umin) <= tol * scale
        finite = np.isfinite(ref_pp)
        assert np.array_equal(finite, np.isfinite(perpart))
        assert np.allclose(
            perpart[finite], ref_pp[finite], rtol=0, atol=tol * scale
        )


def test_duplicate_topic_partition_parity():
    """Duplicate topic+partition entries are legal input (that is what
    -unique exists for); apply_assignment matches by object identity, so
    sessions must stay in lockstep across solvers even with ambiguous keys."""
    pl = wrap(
        [
            P("a", 1, [1, 2], weight=2.0),
            P("a", 1, [1, 3], weight=1.0),  # duplicate key, different replicas
            P("a", 2, [1, 4], weight=1.5),
            P("b", 1, [2, 1], weight=1.0),
        ]
    )
    assert_session_parity(pl, default_rebalance_config(), max_moves=6)


def test_score_window_f32_tolerance_window_soundness():
    """The f32 tier's window tolerance must be a SOUND bound (r5 review):
    the f64 winner's f32 perpart must land inside ``u_min32 + tol`` on
    adversarial regimes — deep near-balance (where the old su-scaled
    tolerance collapses quadratically while the rel-cancellation error
    shrinks only linearly) and mixed heavy/light weights. Also pins the
    greedy parity end-to-end with min_unbalance=0 on those instances."""
    import copy

    import numpy as np

    from kafkabalancer_tpu.balancer.steps import (
        fill_defaults,
        greedy_move,
        validate_weights,
    )
    from kafkabalancer_tpu.models import Partition, PartitionList
    from kafkabalancer_tpu.ops.tensorize import tensorize

    def build(B, P, weight_of):
        parts = []
        for i in range(P):
            a = 1 + (i % B)
            b = 1 + ((a + B // 2 - 1) % B)
            parts.append(
                Partition(
                    topic=f"t{i}", partition=0, replicas=[a, b],
                    weight=weight_of(i), num_replicas=2,
                    brokers=list(range(1, B + 1)), num_consumers=0,
                )
            )
        pl = PartitionList(version=1, partitions=parts)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 0.0
        validate_weights(pl, cfg)
        fill_defaults(pl, cfg)
        return pl, cfg

    rng = random.Random(7)
    cases = [
        # deep near-balance: exact even placement, ppm weight jitter
        build(64, 12 * 64, lambda i: 100.0 * (1 + rng.uniform(-1e-6, 1e-6))),
        # mixed heavy/light: light rows carry the only slack
        build(32, 12 * 32,
              lambda i: 1e-3 * (1 + rng.random()) if i % 7 == 0 else 50.0),
    ]
    for pl, cfg in cases:
        dp = tensorize(pl, cfg)
        loads_map = tpu_solver._oracle_loads(pl, cfg)
        B = dp.bvalid.shape[0]
        loads = np.zeros(B)
        for bid, load in loads_map.items():
            loads[dp.broker_index(bid)] = load
        ints, f64, allowed_arg, all_allowed = tpu_solver._pack_window_args(
            dp, loads, cfg
        )
        o32 = np.asarray(
            tpu_solver._score_window_jit(
                ints, f64.astype(np.float32), allowed_arg,
                leaders=False, all_allowed=all_allowed,
            )
        )
        o64 = np.asarray(
            tpu_solver._score_window_jit(
                ints, f64, allowed_arg, leaders=False,
                all_allowed=all_allowed,
            )
        )
        u32, su32, relmax, wrel = (float(x) for x in o32[:4])
        pp32, pp64 = o32[4:], o64[4:]
        assert np.isfinite(u32)
        rho = 1.0 + relmax + wrel
        eps = float(np.finfo(np.float32).eps)
        tol = eps * (4.0 * B * max(abs(u32), abs(su32)) + 32.0 * rho * rho)
        # the tolerance floor must survive a fully-degenerate objective
        assert tol > 0
        pstar = int(np.argmin(pp64))
        assert pp32[pstar] <= u32 + tol, (pp32[pstar] - u32, tol)

        # end-to-end: device path byte-matches greedy at min_unbalance=0
        old_min = tpu_solver.MIN_DEVICE_CANDIDATES
        tpu_solver.MIN_DEVICE_CANDIDATES = 0
        try:
            g = greedy_move(copy.deepcopy(pl), cfg, False)
            t = tpu_solver.tpu_move_non_leaders(copy.deepcopy(pl), cfg)
        finally:
            tpu_solver.MIN_DEVICE_CANDIDATES = old_min
        gs = None if g is None else [
            (p.topic, p.partition, p.replicas) for p in g.partitions
        ]
        ts = None if t is None else [
            (p.topic, p.partition, p.replicas) for p in t.partitions
        ]
        assert gs == ts
