"""Speculative plan-ahead + the watch-driven continuous controller
(serve/speculate.py; ISSUE 15).

The load-bearing pins:

- the memoized answer is BYTE-IDENTICAL to the live delta path (which
  is itself pinned byte-identical to ``-no-daemon``): speculation can
  make a request faster, never different;
- a mismatching request (drifted digest, changed flags) drops the memo
  and falls back to the live ladder — parity intact;
- speculation never feeds ``serve.requests``/``serve.request_s`` or
  the flight request log, and never resets the idle clock — a daemon
  that is only speculating still honors ``-serve-idle-timeout`` (the
  satellite pin);
- the speculation block's conservation identity is exact at every
  instant: ``attempts == hits + misses + poisoned + memos``;
- a matching request arriving while its answer is still being
  speculated WAITS for it instead of resyncing;
- the watcher plans with no client in the loop: plans stream to the
  emit sink byte-identical to ``-no-daemon`` on the same state, the
  steady state is memo reads, external drift resyncs.
"""

import io
import json
import os
import re
import tempfile
import threading
import time

import pytest

from kafkabalancer_tpu import cli
from kafkabalancer_tpu.codecs import zookeeper as zkmod
from kafkabalancer_tpu.serve import client as sclient
from kafkabalancer_tpu.serve import speculate as spec_mod
from kafkabalancer_tpu.serve.daemon import Daemon

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")

_TS = re.compile(r"^\d{4}/\d{2}/\d{2} \d{2}:\d{2}:\d{2} ", re.M)


def run_cli(args, stdin=""):
    out, err = io.StringIO(), io.StringIO()
    rv = cli.run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


def strip_ts(err: str) -> str:
    return _TS.sub("", err)


def _fixture_state() -> dict:
    with open(FIXTURE) as f:
        return json.load(f)


def _apply_plan(state: dict, plan_stdout: str) -> None:
    plan = json.loads(plan_stdout)
    for entry in plan.get("partitions") or []:
        for row in state["partitions"]:
            if (
                row["topic"] == entry["topic"]
                and row["partition"] == entry["partition"]
            ):
                row["replicas"] = list(entry["replicas"])
                break


def _wait_spec_settled(d, timeout=15.0):
    """Wait until the speculator holds a memo and is out of flight
    (the idle window did its work); returns the stats snapshot."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = d.speculator.stats()
        if st["memos"] >= 1 and not st["inflight"]:
            return st
        time.sleep(0.02)
    return d.speculator.stats()


def _identity_ok(st) -> bool:
    return st["attempts"] == (
        st["hits"] + st["misses"] + st["poisoned"] + st["memos"]
    )


@pytest.fixture
def sock_dir():
    import shutil

    d = tempfile.mkdtemp(prefix="kbspec-")
    yield d
    shutil.rmtree(d, ignore_errors=True)


def _start_daemon(sock, **kw):
    kw.setdefault("idle_timeout", 60.0)
    kw.setdefault("warm", False)
    kw.setdefault("log", lambda _m: None)
    kw.setdefault("speculate", True)
    d = Daemon(sock, **kw)
    rc_box = []
    t = threading.Thread(
        target=lambda: rc_box.append(d.serve_forever()), daemon=True
    )
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if sclient.daemon_alive(sock) is not None:
            return d, t, rc_box
        time.sleep(0.02)
    pytest.fail("daemon never became ready")


@pytest.fixture
def daemon(sock_dir):
    sock = os.path.join(sock_dir, "kb.sock")
    d, t, rc_box = _start_daemon(sock)
    yield sock, d
    sclient.request_shutdown(sock)
    t.join(15)
    assert rc_box == [0], rc_box


# --- the steady state -------------------------------------------------------


def test_steady_state_answers_from_memo_byte_identical(daemon, sock_dir):
    """Register + 3 predicted moves with memoizable argv: every steady
    step after the memo lands answers from it — zero dispatch — and
    stays byte-identical (stdout AND rc; stderr modulo timestamps) to
    -no-daemon. Hits count as requests AND delta hits, so every
    existing reconciliation (request_s count == requests) holds."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    args = ["-input-json", f"-input={input_path}", "-max-reassign=1"]
    for step in range(4):
        with open(input_path, "w") as f:
            json.dump(state, f)
        want_rv, want_out, want_err = run_cli(args + ["-no-daemon"])
        got_rv, got_out, got_err = run_cli(args + [f"-serve-socket={sock}"])
        assert (got_rv, got_out) == (want_rv, want_out), f"step {step}"
        assert strip_ts(got_err) == strip_ts(want_err), f"step {step}"
        _apply_plan(state, want_out)
        _wait_spec_settled(d)
    st = d.speculator.stats()
    assert st["attempts"] >= 3, st
    assert st["hits"] >= 2, st
    assert _identity_ok(st), st
    # memo hits are REAL requests: counted, histogrammed, delta-hit
    assert d._requests == 4
    doc = sclient.fetch_stats(sock)
    assert doc["hists"]["serve.request_s"]["count"] == doc["requests"] == 4
    assert doc["sessions"]["delta_hits"] >= 3
    assert doc["speculation"]["hits"] == st["hits"]
    # the hit wall rides its own histogram too
    assert doc["hists"]["serve.spec.hit_s"]["count"] == st["hits"]
    # per-tenant attribution through the PR-11 families
    tenant = os.path.abspath(input_path)
    assert doc["tenants"]["top"][tenant]["spec_hits"] == st["hits"]
    # flight log carries one record per REAL request (hits included,
    # speculative dispatches excluded)
    trace = sclient.fetch_trace(sock)
    reqs = trace["trace"]["otherData"]["requests"]
    assert len(reqs) == 4
    assert sum(1 for r in reqs if r.get("spec_hit")) == st["hits"]


def test_external_drift_drops_memo_falls_back_correct(daemon, sock_dir):
    """A memo exists but the cluster drifted out-of-band: the request's
    digest matches neither the memo nor the session — counted a MISS,
    answered through the live resync ladder, byte-identical."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    args = ["-input-json", f"-input={input_path}", "-max-reassign=1"]
    with open(input_path, "w") as f:
        json.dump(state, f)
    rv, out, _ = run_cli(args + [f"-serve-socket={sock}"])
    assert rv == 0
    _apply_plan(state, out)
    _wait_spec_settled(d)
    # out-of-band drift the prediction cannot know about
    state["partitions"][0]["replicas"] = [2, 3]
    with open(input_path, "w") as f:
        json.dump(state, f)
    want = run_cli(args + ["-no-daemon"])
    got = run_cli(args + [f"-serve-socket={sock}"])
    assert (got[0], got[1]) == (want[0], want[1])
    st = d.speculator.stats()
    assert st["misses"] >= 1, st
    assert _identity_ok(st), st
    assert st["wasted_dispatches"] == st["misses"] + st["poisoned"]


def test_changed_flags_miss_then_live(daemon, sock_dir):
    """Same digest, different argv (the client added -metrics-json):
    the memo cannot serve it — dropped as a miss, live path answers
    byte-identical (via the rows resync, since speculation advanced
    the resident state past the client's)."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    metrics = os.path.join(sock_dir, "m.json")
    args = ["-input-json", f"-input={input_path}", "-max-reassign=1"]
    with open(input_path, "w") as f:
        json.dump(state, f)
    rv, out, _ = run_cli(args + [f"-serve-socket={sock}"])
    assert rv == 0
    _apply_plan(state, out)
    _wait_spec_settled(d)
    with open(input_path, "w") as f:
        json.dump(state, f)
    want = run_cli(args + ["-no-daemon"])
    got = run_cli(
        args + [f"-serve-socket={sock}", f"-metrics-json={metrics}"]
    )
    assert (got[0], got[1]) == (want[0], want[1])
    payload = json.load(open(metrics))
    assert payload["gauges"]["served"] is True
    st = d.speculator.stats()
    assert st["misses"] >= 1 and _identity_ok(st), st


def test_non_memoizable_argv_never_speculates(daemon, sock_dir):
    """Steps that carry telemetry flags produce per-invocation side
    effects — never memoized, and never even speculated on."""
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    metrics = os.path.join(sock_dir, "m.json")
    args = ["-input-json", f"-input={input_path}", "-max-reassign=1",
            f"-metrics-json={metrics}", f"-serve-socket={sock}"]
    for _step in range(2):
        with open(input_path, "w") as f:
            json.dump(state, f)
        rv, out, _ = run_cli(args)
        assert rv == 0
        _apply_plan(state, out)
    time.sleep(0.3)
    st = d.speculator.stats()
    assert st["attempts"] == 0 and st["memos"] == 0, st


def test_request_waits_for_inflight_speculation(
    daemon, sock_dir, monkeypatch
):
    """A digest-matching request arriving while its answer is still
    being speculated WAITS for the in-flight run and answers from the
    fresh memo — never a resync, never a duplicate dispatch."""
    sock, d = daemon
    started = threading.Event()
    real_run = cli.run

    def slow_internal(i, o, e, args, **kw):
        if threading.current_thread().name.startswith("serve-int-"):
            started.set()
            time.sleep(0.8)
        return real_run(i, o, e, args, **kw)

    monkeypatch.setattr(cli, "run", slow_internal)
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    args = ["-input-json", f"-input={input_path}", "-max-reassign=1"]
    with open(input_path, "w") as f:
        json.dump(state, f)
    rv, out, _ = run_cli(args + [f"-serve-socket={sock}"])
    assert rv == 0
    _apply_plan(state, out)
    assert started.wait(10), "speculation never started"
    # fire the matching next request while speculation is in flight
    with open(input_path, "w") as f:
        json.dump(state, f)
    want = run_cli(args + ["-no-daemon"])
    got = run_cli(args + [f"-serve-socket={sock}"])
    assert (got[0], got[1]) == (want[0], want[1])
    st = d.speculator.stats()
    assert st["hits"] >= 1, st
    assert d.sessions.stats()["resyncs_rows"] == 0, d.sessions.stats()
    assert d.sessions.stats()["resyncs_full"] == 0


def test_real_traffic_preempts_speculation(daemon, sock_dir, monkeypatch):
    """Another tenant's request arriving mid-speculation is never
    stuck behind idle work: the arrival hook preempts, the speculative
    run aborts at its next check, and the live request answers."""
    sock, d = daemon
    started = threading.Event()
    real_run = cli.run

    def slow_internal(i, o, e, args, **kw):
        if threading.current_thread().name.startswith("serve-int-"):
            started.set()
            time.sleep(0.6)
        return real_run(i, o, e, args, **kw)

    monkeypatch.setattr(cli, "run", slow_internal)
    state = _fixture_state()
    a_path = os.path.join(sock_dir, "a.json")
    b_path = os.path.join(sock_dir, "b.json")
    with open(a_path, "w") as f:
        json.dump(state, f)
    with open(b_path, "w") as f:
        json.dump(state, f)
    rv, _out, _ = run_cli(
        ["-input-json", f"-input={a_path}", "-max-reassign=1",
         f"-serve-socket={sock}"]
    )
    assert rv == 0
    assert started.wait(10)
    t0 = time.perf_counter()
    rv_b, out_b, _ = run_cli(
        ["-input-json", f"-input={b_path}", "-max-reassign=1",
         f"-serve-socket={sock}"]
    )
    wall = time.perf_counter() - t0
    assert rv_b == 0 and out_b
    assert wall < 10.0
    # the speculator is out of flight shortly after; its books balance
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        st = d.speculator.stats()
        if not st["inflight"]:
            break
        time.sleep(0.02)
    assert _identity_ok(d.speculator.stats())


def test_release_poisons_live_memo(daemon, sock_dir):
    sock, d = daemon
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    with open(input_path, "w") as f:
        json.dump(state, f)
    rv, _out, _ = run_cli(
        ["-input-json", f"-input={input_path}", "-max-reassign=1",
         f"-serve-socket={sock}"]
    )
    assert rv == 0
    st = _wait_spec_settled(d)
    assert st["memos"] == 1, st
    released = sclient.release_session(sock, os.path.abspath(input_path))
    assert released >= 1
    st = d.speculator.stats()
    assert st["poisoned"] >= 1 and st["memos"] == 0, st
    assert _identity_ok(st), st


def test_speculating_daemon_honors_idle_timeout(sock_dir):
    """THE satellite pin: speculation must not touch the idle clock —
    a daemon whose only post-request activity is speculative planning
    still shuts itself down on -serve-idle-timeout."""
    sock = os.path.join(sock_dir, "kb.sock")
    d, t, rc_box = _start_daemon(sock, idle_timeout=2.0)
    state = _fixture_state()
    input_path = os.path.join(sock_dir, "cluster.json")
    with open(input_path, "w") as f:
        json.dump(state, f)
    t_last = time.monotonic()
    rv, _out, _ = run_cli(
        ["-input-json", f"-input={input_path}", "-max-reassign=1",
         f"-serve-socket={sock}"]
    )
    assert rv == 0
    st = _wait_spec_settled(d)
    assert st["attempts"] >= 1, st  # it DID speculate after the request
    t.join(15)
    assert rc_box == [0], rc_box
    # shutdown at ~idle_timeout after the LAST REQUEST — the
    # speculative run that followed it did not reset the clock
    assert time.monotonic() - t_last < 12.0


def test_speculation_off_by_default_ctor(sock_dir):
    """Directly-constructed daemons (the test-suite shape) keep
    speculation off unless asked; the scrape block still exists with
    the same keys."""
    sock = os.path.join(sock_dir, "kb.sock")
    d, t, rc_box = _start_daemon(sock, speculate=False)
    try:
        state = _fixture_state()
        input_path = os.path.join(sock_dir, "cluster.json")
        with open(input_path, "w") as f:
            json.dump(state, f)
        rv, _out, _ = run_cli(
            ["-input-json", f"-input={input_path}", "-max-reassign=1",
             f"-serve-socket={sock}"]
        )
        assert rv == 0
        time.sleep(0.3)
        doc = sclient.fetch_stats(sock)
        spec = doc["speculation"]
        assert spec["enabled"] is False
        assert spec["attempts"] == 0 and spec["memos"] == 0
    finally:
        sclient.request_shutdown(sock)
        t.join(15)
    assert rc_box == [0]


def test_memo_hit_refreshes_spill_record(sock_dir):
    """The durability invariant moves with the hit: after a memo-hit
    answer, the warm record holds the post-move state the client now
    describes — a restore after SIGKILL still digest-matches."""
    sock = os.path.join(sock_dir, "kb.sock")
    spill_dir = os.path.join(sock_dir, "spill")
    d, t, rc_box = _start_daemon(sock, spill_dir=spill_dir)
    try:
        state = _fixture_state()
        input_path = os.path.join(sock_dir, "cluster.json")
        args = ["-input-json", f"-input={input_path}", "-max-reassign=1",
                f"-serve-socket={sock}"]
        for _step in range(3):
            with open(input_path, "w") as f:
                json.dump(state, f)
            rv, out, _ = run_cli(args)
            assert rv == 0
            _apply_plan(state, out)
            _wait_spec_settled(d)
        assert d.speculator.stats()["hits"] >= 1
        key = next(iter(d.sessions._sessions))
        sess = d.sessions._sessions[key]
        loaded = d.spill.load(key)
        assert loaded is not None
        hdr, _rows = loaded
        # the record predicts the CLIENT's next read (the session's
        # post-hit digest), not the speculation-advanced... the session
        # digest has advanced past it by exactly the live memo
        memo = sess.spec_memo
        assert memo is not None
        assert hdr["digest"] == memo.key_digest
    finally:
        sclient.request_shutdown(sock)
        t.join(15)
    assert rc_box == [0]


def test_memo_retirement_is_exactly_once():
    """The CAS discipline: one memo retires exactly once even when a
    hit and a lifecycle poison race — the conservation identity cannot
    drift."""

    class _D:
        pass

    class _S:
        released = False
        spec_memo = None

    sp = spec_mod.Speculator(_D(), enabled=True)
    sess = _S()
    memo = spec_mod.SpecMemo("d0", [], 0, "", "", "d1")
    sp.attach_memo(sess, memo)
    assert sp.stats()["memos"] == 1
    # a concurrent poison wins; the hit's CAS then fails
    sp.poison_session(sess)
    assert not sp.take_memo(sess, memo)
    sp.retire_miss(sess, memo)  # and a late miss is a no-op too
    st = sp.stats()
    assert (st["hits"], st["misses"], st["poisoned"]) == (0, 0, 1)
    assert _identity_ok(st), st
    # the shed-undo path: take then untake restores the memo intact
    memo2 = spec_mod.SpecMemo("d1", [], 0, "", "", "d2")
    sp.attach_memo(sess, memo2)
    assert sp.take_memo(sess, memo2)
    sp.untake_memo(sess, memo2)
    assert sess.spec_memo is memo2
    st = sp.stats()
    assert st["hits"] == 0 and st["memos"] == 1 and _identity_ok(st)
    # a released session refuses the put-back (consumed stays consumed)
    assert sp.take_memo(sess, memo2)
    sess.released = True
    sp.untake_memo(sess, memo2)
    assert sess.spec_memo is None
    assert _identity_ok(sp.stats())


def test_fixed_point_memo_rearm():
    """The steady-state re-arm: a consumed memo whose answer moved
    nothing (next_digest == key_digest, rc 0) re-attaches instead of
    re-dispatching — a fresh zero-cost attempt, identity undisturbed.
    Anything else (plan advanced, failed rc, slot occupied, released
    session) refuses and falls back to a normal plan-ahead enqueue."""

    class _D:
        pass

    class _S:
        released = False
        spec_memo = None

    sp = spec_mod.Speculator(_D(), enabled=True)
    sess = _S()
    fixed = spec_mod.SpecMemo("d0", [], 0, "out", "", "d0")
    sp.attach_memo(sess, fixed)
    assert sp.take_memo(sess, fixed)
    assert sp.rearm_memo(sess, fixed)
    assert sess.spec_memo is fixed
    st = sp.stats()
    assert (st["attempts"], st["hits"], st["memos"]) == (2, 1, 1)
    assert _identity_ok(st), st
    # the re-armed memo keeps serving the same digest
    assert sp.take_memo(sess, fixed)
    sp.retire_miss(sess, fixed)  # consumed-and-not-rearmed: plain miss
    st = sp.stats()
    assert (st["hits"], st["misses"], st["memos"]) == (2, 0, 0)
    assert _identity_ok(st), st

    # refusals: an advancing plan, a failed rc, an occupied slot, a
    # released session
    moved = spec_mod.SpecMemo("d0", [], 0, "", "", "d1")
    assert not sp.rearm_memo(sess, moved)
    failed = spec_mod.SpecMemo("d0", [], 2, "", "", "d0")
    assert not sp.rearm_memo(sess, failed)
    newer = spec_mod.SpecMemo("d0", [], 0, "", "", "d0")
    sp.attach_memo(sess, newer)
    assert not sp.rearm_memo(sess, fixed)  # a newer memo won the slot
    assert sess.spec_memo is newer
    assert sp.take_memo(sess, newer)
    sess.released = True
    assert not sp.rearm_memo(sess, newer)
    assert sess.spec_memo is None
    sp.retire_miss(sess, newer)
    assert _identity_ok(sp.stats())


def test_watch_flag_validation():
    """-watch without -serve, -watch without a sink, and -watch-emit
    without -watch all refuse loudly (exit 3) — a sink-less watcher
    would plan a move nobody can apply and wait forever."""
    rv, _out, err = run_cli(["-watch=zk:2181"])
    assert rv == 3 and "-watch requires -serve" in err
    rv, _out, err = run_cli(["-serve", "-watch=zk:2181"])
    assert rv == 3 and "requires -watch-emit" in err
    rv, _out, err = run_cli(["-watch-emit=/tmp/x"])
    assert rv == 3 and "-watch-emit requires -watch" in err


def test_abort_check_thread_local_machinery():
    calls = []
    spec_mod.install_abort_check(lambda: calls.append(1))
    try:
        spec_mod.maybe_abort_dispatch()
        assert calls == [1]
    finally:
        spec_mod.install_abort_check(None)
    spec_mod.maybe_abort_dispatch()  # cleared: no-op
    assert calls == [1]

    class _D:
        pass

    sp = spec_mod.Speculator(_D(), enabled=True)
    assert not sp.preempted()
    sp._inflight = spec_mod._Inflight(("t", "s"), "d", [])
    sp.note_real_traffic()
    assert sp.preempted()
    with pytest.raises(spec_mod.SpeculationAborted):
        sp.maybe_abort()
    # SpeculationAborted must NOT be catchable as Exception (the
    # solver fail-open ladders catch Exception broadly)
    assert not issubclass(spec_mod.SpeculationAborted, Exception)


# --- the watcher ------------------------------------------------------------


class _MutableZk:
    """An in-process dict-backed ZK fake whose whole tree swaps
    atomically (one attribute rebind) — used by the watcher tests."""

    def __init__(self):
        self.tree = {}

    # kazoo surface
    def start(self, timeout=10):
        pass

    def stop(self):
        pass

    def close(self):
        pass

    def get_children(self, path, watcher=None):
        return sorted(self.tree)

    def get(self, path, watcher=None):
        name = path.rsplit("/", 1)[1]
        return json.dumps(
            {"version": 1, "partitions": self.tree[name]}
        ).encode("utf-8"), None


@pytest.fixture
def fake_zk():
    zk = _MutableZk()
    zkmod.set_zk_client_factory(lambda hosts: zk)
    yield zk
    zkmod.set_zk_client_factory(None)


def _zk_oracle_input(tree) -> str:
    rows = [
        {"topic": t, "partition": int(pid), "replicas": tree[t][pid]}
        for t in sorted(tree)
        for pid in sorted(tree[t], key=int)
    ]
    return json.dumps({"version": 1, "partitions": rows})


def test_watcher_plans_with_no_client_and_hits_memo(sock_dir, fake_zk):
    """The continuous controller end to end, in process: the watcher
    reads the (fake) ZK tree, emits plans byte-identical to -no-daemon
    on the same state, consumes the speculator's memo once the applier
    confirms each move, resyncs on out-of-band drift — and the daemon
    serves ZERO client plan ops throughout."""
    fake_zk.tree = {"w": {str(i): [0, 1] for i in range(8)}}
    fake_zk.tree["w"]["0"] = [2, 3]
    emit = os.path.join(sock_dir, "plans")
    sock = os.path.join(sock_dir, "kb.sock")
    d, t, rc_box = _start_daemon(
        sock,
        idle_timeout=0.0,
        watch_conn="fake:2181",
        watch_emit=emit,
        watch_poll=0.1,
        watch_argv=["-no-daemon=true", "-max-reassign=1"],
    )
    try:
        seen = 0
        parity_rounds = 0
        for _round in range(5):
            path = None
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                files = sorted(
                    f for f in os.listdir(emit) if f.endswith(".json")
                ) if os.path.isdir(emit) else []
                if len(files) > seen:
                    path = os.path.join(emit, files[seen])
                    break
                time.sleep(0.03)
            if path is None:
                break
            want = run_cli(
                ["-input-json", "-max-reassign=1", "-no-daemon"],
                stdin=_zk_oracle_input(fake_zk.tree),
            )
            got = open(path).read()
            assert got == want[1], f"round {_round}"
            parity_rounds += 1
            # the applier role: apply the emitted plan to the fake tree
            tree = json.loads(json.dumps(fake_zk.tree))
            _apply = json.loads(got)
            for entry in _apply.get("partitions") or []:
                tree[entry["topic"]][str(entry["partition"])] = list(
                    entry["replicas"]
                )
            fake_zk.tree = tree
            seen += 1
        assert parity_rounds >= 3
        w = sclient.fetch_watch(sock)
        assert w is not None
        assert w["watch"]["plans_emitted"] >= 3
        assert w["watch"]["spec_hits"] >= 1, w["watch"]
        assert w["watch"]["errors"] == 0
        assert w["watch"]["last_event_lag_s"] is not None
        assert _identity_ok(w["speculation"])
        # no client ever planned
        assert sclient.fetch_stats(sock)["requests"] == 0
        # out-of-band drift: flip a replica set under the watcher
        tree = json.loads(json.dumps(fake_zk.tree))
        tree["w"]["3"] = [4, 5]
        fake_zk.tree = tree
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            w2 = (sclient.fetch_watch(sock) or {}).get("watch") or {}
            if w2.get("resyncs", 0) >= 1:
                break
            time.sleep(0.05)
        assert w2.get("resyncs", 0) >= 1, w2
    finally:
        sclient.request_shutdown(sock)
        t.join(20)
    assert rc_box == [0]


def test_watch_disabled_block_and_op(daemon):
    """A watch-less daemon still answers the `watch` op and carries
    the disabled block with the full key set."""
    sock, _d = daemon
    doc = sclient.fetch_stats(sock)
    w = doc["watch"]
    assert w["enabled"] is False
    assert set(w) == set(
        spec_mod.ZkWatcher.disabled_stats()
    )
    resp = sclient.fetch_watch(sock)
    assert resp is not None and resp["watch"]["enabled"] is False
