"""Differential pins for the vectorized greedy scan (steps.scan_moves).

scan_partition_move is the parity oracle (a faithful transcription of the
reference move() loop body); scan_moves is its batched numpy replay. The
contract is BIT equality — same cu double, same (partition, replica,
target) winner — because the greedy scan is itself the byte-parity oracle
for the device solvers, and any float drift here would cascade into plan
differences downstream.
"""

import copy
import random

import pytest

from kafkabalancer_tpu.balancer import costmodel
from kafkabalancer_tpu.balancer.steps import (
    BalanceError,
    fill_defaults,
    greedy_move,
    scan_moves,
    scan_partition_move,
)
from kafkabalancer_tpu.models import Partition, PartitionList
from kafkabalancer_tpu.models.config import default_rebalance_config
from tests.helpers import random_partition_list


def _bl_of(pl, cfg):
    loads = costmodel.get_broker_load(pl)
    for bid in cfg.brokers or []:
        if bid not in loads:
            loads[bid] = 0.0
    return costmodel.get_bl(loads)


def _sequential(parts, bl, cu, best, cfg, leaders):
    """The scalar oracle, threaded exactly like greedy_move does."""
    winner = -1
    for pos, p in enumerate(parts):
        cu, nbest = scan_partition_move(p, bl, cu, best, cfg, leaders)
        if nbest is not best:
            best, winner = nbest, pos
    return cu, best, winner


def _assert_scan_parity(pl, cfg, leaders=False):
    parts = list(pl.iter_partitions())
    bl_a = _bl_of(pl, cfg)
    bl_b = copy.deepcopy(bl_a)
    su = costmodel.get_unbalance_bl(bl_a)
    cu_s, best_s, pos_s = _sequential(parts, bl_a, su, None, cfg, leaders)
    cu_v, best_v, pos_v = scan_moves(parts, bl_b, su, None, cfg, leaders)
    # bit equality, NaN-aware (an all-zero-loads cluster keeps cu = NaN)
    assert repr(cu_s) == repr(cu_v), (cu_s, cu_v)
    assert pos_s == pos_v
    if best_s is None:
        assert best_v is None
    else:
        ps, rs, bs = best_s
        pv, rv, bv = best_v
        assert ps is pv  # same partition OBJECT: replace_replica needs it
        assert (rs, bs) == (rv, bv)
    # the batch path must leave bl untouched (the scalar restores it)
    assert bl_a == bl_b


@pytest.mark.parametrize("seed", range(25))
def test_scan_moves_randomized_bit_parity(seed):
    rng = random.Random(seed)
    pl = random_partition_list(
        rng,
        n_partitions=rng.randint(1, 60),
        n_brokers=rng.randint(2, 12),
        max_rf=4,
        with_consumers=True,
        restrict_brokers=True,
        filled=True,
    )
    cfg = default_rebalance_config()
    cfg.min_unbalance = 0.0
    _assert_scan_parity(pl, cfg, leaders=False)
    _assert_scan_parity(pl, cfg, leaders=True)


@pytest.mark.parametrize("seed", [3, 7, 19])
def test_scan_moves_chunk_invariant(seed):
    """The oracle-side CHUNKED replay: scan_moves' running strict-<
    minimum replays identically at ANY chunk size (1-candidate chunks,
    a prime width, the default) — the same combine argument the sharded
    scale tier's per-row-block winner combine relies on, pinned here on
    the scalar oracle itself."""
    rng = random.Random(4000 + seed)
    pl = random_partition_list(
        rng,
        n_partitions=rng.randint(8, 60),
        n_brokers=rng.randint(3, 12),
        max_rf=4,
        with_consumers=True,
        restrict_brokers=True,
        filled=True,
    )
    cfg = default_rebalance_config()
    cfg.min_unbalance = 0.0
    parts = list(pl.iter_partitions())
    for leaders in (False, True):
        bl = _bl_of(pl, cfg)
        su = costmodel.get_unbalance_bl(bl)
        base = scan_moves(parts, copy.deepcopy(bl), su, None, cfg, leaders)
        for chunk in (1, 7, 8192):
            got = scan_moves(
                parts, copy.deepcopy(bl), su, None, cfg, leaders,
                chunk=chunk,
            )
            assert repr(got[0]) == repr(base[0]), (chunk, leaders)
            assert got[1] is base[1] or got[1] == base[1]
            assert got[2] == base[2]


def test_replay_broker_loads_exact_op_order():
    """replay_broker_loads applies one subtract + one add per move, in
    move order, and never mutates the input table."""
    from kafkabalancer_tpu.balancer.steps import replay_broker_loads

    bl = [[1, 0.1], [2, 0.2], [3, 0.3]]
    snapshot = copy.deepcopy(bl)
    out = replay_broker_loads(bl, [(1, 3, 0.05), (3, 2, 0.025)])
    assert bl == snapshot
    assert out[0][1] == 0.1 - 0.05
    assert out[2][1] == (0.3 + 0.05) - 0.025
    assert out[1][1] == 0.2 + 0.025


@pytest.mark.parametrize("seed", range(10))
def test_get_broker_load_bit_matches_reference(seed):
    """The np.add.at accumulation must reproduce the reference dict
    loop's per-broker float sums to the last bit (same accrual order per
    broker cell), including the leader premium and consumer terms."""
    rng = random.Random(1000 + seed)
    pl = random_partition_list(
        rng,
        n_partitions=rng.randint(0, 80),
        n_brokers=rng.randint(2, 10),
        max_rf=4,
        with_consumers=True,
        filled=True,
    )
    fast = costmodel.get_broker_load(pl)
    ref = costmodel._get_broker_load_ref(pl)
    assert set(fast) == set(ref)
    for bid in ref:
        assert repr(fast[bid]) == repr(ref[bid]), bid


def test_scan_moves_zero_loads_nan_objective():
    """All-zero loads: the objective is NaN end to end and no candidate
    may ever win (NaN < NaN is False) — the reference's no-candidate
    exit-0 contract."""
    parts = [
        Partition(
            topic="t", partition=i, replicas=[1, 2], weight=0.0,
            num_replicas=2, brokers=[1, 2, 3], num_consumers=0,
        )
        for i in range(4)
    ]
    pl = PartitionList(version=1, partitions=parts)
    cfg = default_rebalance_config()
    _assert_scan_parity(pl, cfg)


def test_scan_moves_min_replicas_filter_and_empty_movable():
    """Partitions under min_replicas_for_rebalancing and RF-1 partitions
    (no movable follower) are skipped identically."""
    parts = [
        Partition(
            topic="t", partition=0, replicas=[1], weight=1.0,
            num_replicas=1, brokers=[1, 2, 3], num_consumers=0,
        ),
        Partition(
            topic="t", partition=1, replicas=[1, 2], weight=5.0,
            num_replicas=2, brokers=[1, 2, 3], num_consumers=0,
        ),
    ]
    pl = PartitionList(version=1, partitions=parts)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 0.0
    _assert_scan_parity(pl, cfg)


def test_scan_moves_missing_replica_raises_like_oracle():
    """A replica absent from the broker-load table raises the same
    BalanceError (message included) as the scalar scan."""
    good = Partition(
        topic="t", partition=0, replicas=[1, 2], weight=1.0,
        num_replicas=2, brokers=[1, 2], num_consumers=0,
    )
    pl = PartitionList(version=1, partitions=[good])
    cfg = default_rebalance_config()
    bl = _bl_of(pl, cfg)
    bad = Partition(
        topic="t", partition=1, replicas=[1, 99], weight=1.0,
        num_replicas=2, brokers=[1, 2], num_consumers=0,
    )
    with pytest.raises(BalanceError) as e_seq:
        _sequential([good, bad], copy.deepcopy(bl), 0.0, None, cfg, False)
    with pytest.raises(BalanceError) as e_vec:
        scan_moves([good, bad], copy.deepcopy(bl), 0.0, None, cfg, False)
    assert str(e_seq.value) == str(e_vec.value)


def test_greedy_move_still_byte_stable():
    """End-to-end: greedy_move (now on the batched scan) still produces
    the documented winner on a hand-built unbalanced cluster."""
    parts = [
        Partition(
            topic="t", partition=i, replicas=[1, 2], weight=1.0,
            num_replicas=2, brokers=[1, 2, 3], num_consumers=0,
        )
        for i in range(6)
    ]
    pl = PartitionList(version=1, partitions=parts)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 0.0
    cfg.brokers = [1, 2, 3]  # zero-fills idle broker 3 into the table
    fill_defaults(pl, cfg)
    out = greedy_move(pl, cfg, False)
    assert out is not None
    moved = out.partitions[0]
    # first-strict-improver: partition 0's follower moves to the idle
    # broker 3
    assert (moved.topic, moved.partition) == ("t", 0)
    assert moved.replicas == [1, 3]
