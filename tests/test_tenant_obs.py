"""Bounded label-dimensioned telemetry families (obs/hist.py
HistFamily, obs/metrics.py CounterFamily) and their export surfaces.

The load-bearing pins:

- the label bound is HARD: past ``cap`` live labels the LRU label is
  demoted into the ``other`` rollup — a million-tenant label churn can
  never grow family memory past cap+1 histograms;
- demotion is LOSSLESS in aggregate: the family-wide observation total
  is exact and monotone across any amount of churn (the rollup absorbs
  every demoted observation, lifetime AND windowed);
- the families survive per-invocation registry resets (daemon-lifetime,
  like the plain histograms) and clear on ``reset_tenants``;
- the Prometheus exposition renders tenants as LABELED series (bounded
  cardinality, escaped label values) and re-emits the name-embedded
  per-lane hists as lane-labeled series beside the deprecated names.
"""

import threading

from kafkabalancer_tpu.obs.hist import (
    OTHER_LABEL,
    HistFamily,
    StreamingHist,
    bucket_le,
)
from kafkabalancer_tpu.obs.metrics import CounterFamily, MetricsRegistry


# --- HistFamily -----------------------------------------------------------


def test_hist_family_demotes_lru_into_other():
    f = HistFamily(cap=2)
    f.observe("a", 1.0)
    f.observe("b", 2.0)
    f.observe("a", 1.5)  # bumps a's recency: b is now the LRU
    f.observe("c", 4.0)  # cap exceeded: b demotes into other
    snap = f.snapshot()
    assert sorted(snap["labels"]) == ["a", "c"]
    assert snap["demoted"] == 1
    assert snap["other"]["count"] == 1  # b's one observation
    assert snap["other"]["max"] == 2.0
    # a demoted label coming back starts fresh; its history stays in
    # the rollup (a is now the LRU and demotes with BOTH its samples)
    f.observe("b", 8.0)
    snap = f.snapshot()
    assert sorted(snap["labels"]) == ["b", "c"]
    assert snap["demoted"] == 2
    assert snap["other"]["count"] == 3  # b's old 1 + a's 2
    assert snap["labels"]["b"]["count"] == 1  # fresh, not resurrected


def test_hist_family_rollup_total_monotone_across_churn():
    """The family-wide total equals the observation count exactly, no
    matter how labels churn through the cap."""
    f = HistFamily(cap=3)
    n = 0
    for i in range(200):
        f.observe(f"tenant-{i % 17}", float(i % 7 + 1))
        n += 1
        assert f.total_count() == n
    snap = f.snapshot()
    in_labels = sum(h["count"] for h in snap["labels"].values())
    assert in_labels + snap["other"]["count"] == 200
    assert len(snap["labels"]) == 3
    # the 17-label cycle never revisits a label while it is still live
    # (cap 3 < 17), so every observation past the first 3 demotes one
    assert snap["demoted"] == 200 - 3


def test_hist_family_reserved_other_label_feeds_rollup():
    f = HistFamily(cap=2)
    f.observe(OTHER_LABEL, 3.0)
    snap = f.snapshot()
    assert snap["labels"] == {}
    assert snap["other"]["count"] == 1


def test_hist_family_windowed_view_rotation_under_churn():
    """Windowed state follows a demoted label into the rollup when
    still fresh, and ages out of it on the normal ring schedule."""
    clock = [0.0]
    f = HistFamily(cap=1, window_s=60.0, ring=6, now=lambda: clock[0])
    f.observe("a", 1.0)
    clock[0] = 5.0
    f.observe("b", 2.0)  # demotes a at t=5: its t=0 slot is still live
    other = f.snapshot()["other"]
    assert other["count"] == 1
    assert other["window"]["count"] == 1  # a's fresh slot merged in
    # age the window out: the rollup's LIFETIME keeps a's observation,
    # the windowed view drops it
    clock[0] = 120.0
    other = f.snapshot()["other"]
    assert other["count"] == 1
    assert other["window"]["count"] == 0


def test_hist_family_demotion_never_recycles_newer_window_slots():
    """A demoted label whose ring slots are OLDER than what the rollup
    already holds in those positions must not wipe the rollup's newer
    sub-epochs (merge_from's epoch guard)."""
    clock = [0.0]
    f = HistFamily(cap=1, window_s=60.0, ring=6, now=lambda: clock[0])
    f.observe("a", 1.0)  # a's slot: epoch 0
    clock[0] = 61.0  # one full window later
    f.observe(OTHER_LABEL, 9.0)  # rollup slot: same ring position, newer
    f.observe("b", 2.0)  # demotes a; a's epoch-0 slot is stale
    other = f.snapshot()["other"]
    assert other["count"] == 2  # lifetime keeps both
    assert other["window"]["count"] == 1  # only the fresh observation


def test_streaming_hist_merge_from_matches_combined_stream():
    a, b = StreamingHist(), StreamingHist()
    combined = StreamingHist()
    vals_a = [0.001, 0.01, 0.5, 3.0]
    vals_b = [0.002, 0.2, 7.0]
    for v in vals_a:
        a.observe(v)
        combined.observe(v)
    for v in vals_b:
        b.observe(v)
        combined.observe(v)
    a.merge_from(b)
    sa, sc = a.snapshot(), combined.snapshot()
    for key in ("count", "min", "max", "p50", "p95", "p99", "buckets"):
        assert sa[key] == sc[key], key
    assert abs(sa["sum"] - sc["sum"]) < 1e-9


# --- CounterFamily --------------------------------------------------------


def test_counter_family_demotion_preserves_total():
    f = CounterFamily(cap=2)
    total = 0.0
    for i, label in enumerate("abcabcddee"):
        f.add(label, float(i + 1))
        total += i + 1
        assert f.total() == total
    snap = f.snapshot()
    assert len(snap["labels"]) == 2
    assert snap["other"] + sum(snap["labels"].values()) == total
    assert snap["demoted"] >= 3


def test_counter_family_other_is_reserved():
    f = CounterFamily(cap=1)
    f.add(OTHER_LABEL, 5.0)
    f.add("a", 1.0)
    assert f.get(OTHER_LABEL) == 5.0
    assert f.get("a") == 1.0
    assert f.snapshot()["demoted"] == 0


# --- concurrency ----------------------------------------------------------


def test_family_concurrency_hammer():
    """The registry-hammer mirror for the label families: concurrent
    observers churning labels through the cap, with readers racing
    snapshots — the final totals must be exact (no lost or
    double-counted observation at the demotion boundary)."""
    hf = HistFamily(cap=4)
    cf = CounterFamily(cap=4)
    n_threads, n_obs = 8, 500
    stop = threading.Event()

    def writer(k: int) -> None:
        for i in range(n_obs):
            label = f"tenant-{(i * (k + 3)) % 23}"
            hf.observe(label, float(i % 9 + 1))
            cf.add(label)

    ceiling = n_threads * n_obs

    def reader() -> None:
        while not stop.is_set():
            snap = hf.snapshot()
            live = sum(h["count"] for h in snap["labels"].values())
            other = snap["other"]["count"] if snap["other"] else 0
            # every snapshot is internally consistent: nothing counted
            # both live and rolled-up (<= the eventual total), and the
            # monotone total never overshoots
            assert live + other <= ceiling
            assert cf.total() <= ceiling

    threads = [
        threading.Thread(target=writer, args=(k,))
        for k in range(n_threads)
    ]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in readers:
        t.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert hf.total_count() == n_threads * n_obs
    assert cf.total() == float(n_threads * n_obs)
    assert len(hf.snapshot()["labels"]) <= 4


# --- registry integration -------------------------------------------------


def test_registry_tenant_families_survive_reset():
    r = MetricsRegistry()
    r.tenant_hist_observe("serve.request_s", "t0", 0.5)
    r.tenant_count("serve.requests", "t0")
    r.reset()  # the per-invocation epoch boundary
    snap = r.tenant_snapshot()
    assert snap["hists"]["serve.request_s"]["labels"]["t0"]["count"] == 1
    assert snap["counters"]["serve.requests"]["labels"]["t0"] == 1.0
    assert r.tenant_counter_get("serve.requests", "t0") == 1.0
    r.reset_tenants()
    assert r.tenant_snapshot() == {"hists": {}, "counters": {}}


def test_registry_tenant_family_cap_binds_at_creation():
    r = MetricsRegistry()
    fam = r.tenant_hist("serve.request_s", cap=2)
    assert r.tenant_hist("serve.request_s", cap=99) is fam
    for i in range(5):
        fam.observe(f"t{i}", 1.0)
    assert len(fam.snapshot()["labels"]) == 2


# --- export surfaces ------------------------------------------------------


def _tenants_doc():
    hist = {
        "count": 3, "sum": 0.3, "min": 0.05, "max": 0.15,
        "p50": 0.1, "p95": 0.15, "p99": 0.15,
        "window": {
            "count": 3, "span_s": 60.0, "p50": 0.1, "p95": 0.15,
            "p99": 0.15,
        },
        "buckets": [[0.1, 2], [0.15, 1]],
    }
    return {
        "requests": 7,
        "hists": {
            "serve.lane0.queue_depth": dict(hist),
            "serve.lane1.queue_depth": dict(hist),
            "serve.lane0.occupancy": dict(hist),
            "serve.request_s": dict(hist),
        },
        "tenants": {
            "cap": 32, "demoted": 4,
            "top": {
                'ten"ant\\1': {
                    "requests": 3, "crashed": 0, "request_s": dict(hist),
                    "queue_s": None, "delta_hits": 2, "resyncs_rows": 1,
                    "resyncs_full": 0, "fallbacks": 1, "sessions": 1,
                    "session_bytes": 2048,
                },
            },
            "other": {
                "requests": 4, "crashed": 1, "request_s": dict(hist),
                "queue_s": None, "delta_hits": 0, "resyncs_rows": 0,
                "resyncs_full": 2, "fallbacks": 3, "sessions": 0,
                "session_bytes": 0,
            },
        },
    }


def test_prometheus_tenant_series_and_escaping():
    from kafkabalancer_tpu.obs import export as obs_export

    text = obs_export.render_prometheus(_tenants_doc())
    # escaped label value: backslash and quote both survive safely
    assert (
        'kafkabalancer_tpu_tenant_requests{tenant="ten\\"ant\\\\1"} 3'
        in text
    )
    assert 'kafkabalancer_tpu_tenant_requests{tenant="other"} 4' in text
    assert 'kafkabalancer_tpu_tenant_delta_hits{tenant="ten\\"ant\\\\1"} 2' in text
    assert 'kafkabalancer_tpu_tenant_session_bytes{tenant="ten\\"ant\\\\1"} 2048' in text
    assert "# TYPE kafkabalancer_tpu_tenants_demoted counter" in text
    assert "kafkabalancer_tpu_tenants_demoted 4" in text
    assert (
        'kafkabalancer_tpu_tenant_request_s{tenant="other",quantile="0.99"}'
        in text
    )
    assert 'kafkabalancer_tpu_tenant_request_s_count{tenant="other"} 3' in text


def test_prometheus_lane_labeled_series_beside_deprecated_names():
    from kafkabalancer_tpu.obs import export as obs_export

    text = obs_export.render_prometheus(_tenants_doc())
    # the deprecated name-embedded spelling still emits...
    assert "# TYPE kafkabalancer_tpu_serve_lane0_queue_depth summary" in text
    # ...and the labeled series rides beside it, one metric per kind
    assert "# TYPE kafkabalancer_tpu_serve_lane_queue_depth summary" in text
    assert (
        'kafkabalancer_tpu_serve_lane_queue_depth{lane="0",quantile="0.5"}'
        in text
    )
    assert (
        'kafkabalancer_tpu_serve_lane_queue_depth{lane="1",quantile="0.5"}'
        in text
    )
    assert 'kafkabalancer_tpu_serve_lane_queue_depth_count{lane="1"} 3' in text
    assert (
        'kafkabalancer_tpu_serve_lane_occupancy{lane="0",quantile="0.99"}'
        in text
    )
    # the plain request hist is untouched by the lane re-labeling
    assert "# TYPE kafkabalancer_tpu_serve_request_s summary" in text


def test_serve_stats_human_rendering_top_tenants_table():
    from kafkabalancer_tpu.obs import export as obs_export

    text = obs_export.render_serve_stats(_tenants_doc())
    assert "tenants: 2 tracked (cap 32, 4 demoted into other)" in text
    assert "requests  p50" in text  # the table header
    assert "(other)" in text
    # delta-hit rate: 2 hits of 3 requests
    assert "67%" in text
    # resident bytes
    assert "2.0KB" in text


def test_bucket_le_sanity():
    # the replay harness leans on bucket arithmetic; pin the contract
    assert bucket_le(0) == 1.0
    assert bucket_le(4) == 2.0
