"""Zookeeper codec: happy path against a fake kazoo client.

The reference leaves its ZK happy path untested (only the bad-connection-
string error path, kafkabalancer_test.go:145-154); round 1 matched that.
These tests close the gap with an in-memory kazoo stand-in covering the
topics -> partitions -> replicas walk, ordering, topic filtering, and
mid-walk failure mapping (codecs.go:95-135).
"""

import io
import json
import sys
import types

import pytest

from kafkabalancer_tpu.codecs.readers import CodecError
from kafkabalancer_tpu.codecs.zookeeper import (
    get_partition_list_from_zookeeper,
)


class FakeKazooClient:
    """Minimal kazoo.client.KazooClient: /brokers/topics tree reads."""

    tree = {}
    fail_topic = None
    started = []

    def __init__(self, hosts, read_only=False):
        self.hosts = hosts
        type(self).started.append(hosts)

    def start(self, timeout=None):
        pass

    def get_children(self, path):
        assert path == "/brokers/topics"
        return list(self.tree)  # deliberately unsorted

    def get(self, path):
        topic = path.rsplit("/", 1)[1]
        if topic == self.fail_topic:
            raise RuntimeError("zk read boom")
        state = {"version": 3, "partitions": self.tree[topic]}
        return json.dumps(state).encode("utf-8"), object()

    def stop(self):
        pass

    def close(self):
        pass


@pytest.fixture
def fake_kazoo(monkeypatch):
    mod = types.ModuleType("kazoo")
    client_mod = types.ModuleType("kazoo.client")
    client_mod.KazooClient = FakeKazooClient
    mod.client = client_mod
    monkeypatch.setitem(sys.modules, "kazoo", mod)
    monkeypatch.setitem(sys.modules, "kazoo.client", client_mod)
    FakeKazooClient.tree = {
        "zebra": {"0": [3, 1], "1": [1, 2]},
        "alpha": {"0": [1, 2], "10": [2, 3], "9": [3, 2]},
    }
    FakeKazooClient.fail_topic = None
    FakeKazooClient.started = []
    return FakeKazooClient


def test_zk_happy_path_walk_and_ordering(fake_kazoo):
    pl = get_partition_list_from_zookeeper("zk1:2181,zk2:2181/kafka")
    # chroot rides the hosts string (kazoo-go semantics)
    assert fake_kazoo.started == ["zk1:2181,zk2:2181/kafka"]
    got = [(p.topic, p.partition, p.replicas) for p in pl.iter_partitions()]
    # topics sorted lexically; partitions sorted NUMERICALLY (9 before 10)
    assert got == [
        ("alpha", 0, [1, 2]),
        ("alpha", 9, [3, 2]),
        ("alpha", 10, [2, 3]),
        ("zebra", 0, [3, 1]),
        ("zebra", 1, [1, 2]),
    ]
    # enrichment left unset like the reference's TODO (codecs.go:128-129)
    for p in pl.iter_partitions():
        assert p.weight == 0.0 and p.num_consumers == 0.0


def test_zk_topic_filter(fake_kazoo):
    pl = get_partition_list_from_zookeeper("zk1:2181", topics=["zebra"])
    assert {p.topic for p in pl.iter_partitions()} == {"zebra"}
    assert len(pl) == 2


def test_zk_midwalk_failure_maps_to_codec_error(fake_kazoo):
    fake_kazoo.fail_topic = "zebra"
    with pytest.raises(CodecError, match="topic zebra"):
        get_partition_list_from_zookeeper("zk1:2181")


def test_zk_cli_end_to_end(fake_kazoo):
    """-from-zk through run(): full pipeline on the fake cluster."""
    from kafkabalancer_tpu.cli import run

    out, err = io.StringIO(), io.StringIO()
    rv = run(
        io.StringIO(""), out, err,
        ["kafkabalancer", "-from-zk=zk1:2181", "-max-reassign=1"],
    )
    assert rv == 0, err.getvalue()
    plan = json.loads(out.getvalue())
    assert plan["version"] == 1


def test_zk_cli_error_paths_unchanged(fake_kazoo):
    from kafkabalancer_tpu.cli import run

    out, err = io.StringIO(), io.StringIO()
    rv = run(io.StringIO(""), out, err, ["kafkabalancer", "-from-zk=."])
    assert rv == 2
    assert "failed parsing zk connection string" in err.getvalue()


# --- the watch machinery (ISSUE 15): factory seam, event decode, -----------
# --- watcher registration, and the cross-process file fake -----------------

from kafkabalancer_tpu.codecs import zookeeper as zkmod  # noqa: E402


@pytest.fixture
def client_factory():
    """The injectable-client seam (set_zk_client_factory) — wins over
    kazoo AND the env fake; always uninstalled."""
    created = []

    def install(tree, watch_support=True):
        def factory(hosts):
            zk = FakeKazooClient(hosts)
            zk.tree_local = tree
            if not watch_support:
                # simulate a client whose get/get_children take no
                # watcher argument at all
                def gc(path):
                    assert path == "/brokers/topics"
                    return list(tree)

                def g(path):
                    topic = path.rsplit("/", 1)[1]
                    state = {"version": 3, "partitions": tree[topic]}
                    return json.dumps(state).encode("utf-8"), object()

                zk.get_children = gc
                zk.get = g
            created.append(zk)
            return zk

        zkmod.set_zk_client_factory(factory)
        return created

    yield install
    zkmod.set_zk_client_factory(None)


def test_watch_event_decode():
    """The znode payload decode the -watch loop shares with the
    one-shot read: numeric pid order, int-coerced replica ids, empty
    state tolerated."""
    parts = zkmod.decode_topic_state(
        "t",
        json.dumps(
            {"version": 1, "partitions": {"11": [1], "2": ["3", 4]}}
        ).encode("utf-8"),
    )
    assert [(p.partition, p.replicas) for p in parts] == [
        (2, [3, 4]), (11, [1]),
    ]
    assert zkmod.decode_topic_state("t", b'{"version":1}') == []


def test_factory_seam_wins_and_watcher_registers(client_factory, fake_kazoo):
    """make_zk_client + read_cluster with a watcher: the factory's
    client is used (chroot on the hosts string), kazoo-style watch
    callbacks are registered on the children node and every topic."""
    registered = []

    class WatchingFake(FakeKazooClient):
        def get_children(self, path, watcher=None):
            if watcher is not None:
                registered.append(("children", watcher))
            return super().get_children(path)

        def get(self, path, watcher=None):
            if watcher is not None:
                registered.append((path.rsplit("/", 1)[1], watcher))
            return super().get(path)

    zkmod.set_zk_client_factory(lambda hosts: WatchingFake(hosts))
    zk = zkmod.make_zk_client("h1:2181,h2:2182/kafka")
    assert zk.hosts == "h1:2181,h2:2182/kafka"
    cb = lambda *a: None  # noqa: E731
    pl = zkmod.read_cluster(zk, watcher=cb)
    assert len(pl) == 5
    assert [k for k, _w in registered] == ["children", "alpha", "zebra"]
    assert all(w is cb for _k, w in registered)


def test_watcherless_client_falls_back(client_factory, fake_kazoo):
    """A client whose get/get_children accept NO watcher argument
    (TypeError) still reads — the poll interval is the fallback."""
    client_factory(FakeKazooClient.tree, watch_support=False)
    zk = zkmod.make_zk_client("h:2181")
    pl = zkmod.read_cluster(zk, watcher=lambda *a: None)
    assert len(pl) == 5


def test_file_zk_client_roundtrip(tmp_path, monkeypatch):
    """The cross-process $KAFKABALANCER_TPU_FAKE_ZK seam: topic files
    under <root>/brokers/topics, half-written .tmp publishes invisible
    to readers."""
    tdir = tmp_path / "zk" / "brokers" / "topics"
    tdir.mkdir(parents=True)
    (tdir / "ft").write_text(
        json.dumps({"version": 1, "partitions": {"0": [1, 2], "1": [2, 3]}})
    )
    (tdir / "ft.tmp").write_text("{torn write")
    monkeypatch.setenv("KAFKABALANCER_TPU_FAKE_ZK", str(tmp_path / "zk"))
    pl = get_partition_list_from_zookeeper("fake:2181")
    assert [
        (p.topic, p.partition, p.replicas) for p in pl.iter_partitions()
    ] == [("ft", 0, [1, 2]), ("ft", 1, [2, 3])]


def test_file_zk_client_missing_root(tmp_path, monkeypatch):
    monkeypatch.setenv(
        "KAFKABALANCER_TPU_FAKE_ZK", str(tmp_path / "absent")
    )
    with pytest.raises(CodecError) as ei:
        get_partition_list_from_zookeeper("fake:2181")
    assert str(ei.value).startswith("failed reading topic list from zk")
