"""Zookeeper codec: happy path against a fake kazoo client.

The reference leaves its ZK happy path untested (only the bad-connection-
string error path, kafkabalancer_test.go:145-154); round 1 matched that.
These tests close the gap with an in-memory kazoo stand-in covering the
topics -> partitions -> replicas walk, ordering, topic filtering, and
mid-walk failure mapping (codecs.go:95-135).
"""

import io
import json
import sys
import types

import pytest

from kafkabalancer_tpu.codecs.readers import CodecError
from kafkabalancer_tpu.codecs.zookeeper import (
    get_partition_list_from_zookeeper,
)


class FakeKazooClient:
    """Minimal kazoo.client.KazooClient: /brokers/topics tree reads."""

    tree = {}
    fail_topic = None
    started = []

    def __init__(self, hosts, read_only=False):
        self.hosts = hosts
        type(self).started.append(hosts)

    def start(self, timeout=None):
        pass

    def get_children(self, path):
        assert path == "/brokers/topics"
        return list(self.tree)  # deliberately unsorted

    def get(self, path):
        topic = path.rsplit("/", 1)[1]
        if topic == self.fail_topic:
            raise RuntimeError("zk read boom")
        state = {"version": 3, "partitions": self.tree[topic]}
        return json.dumps(state).encode("utf-8"), object()

    def stop(self):
        pass

    def close(self):
        pass


@pytest.fixture
def fake_kazoo(monkeypatch):
    mod = types.ModuleType("kazoo")
    client_mod = types.ModuleType("kazoo.client")
    client_mod.KazooClient = FakeKazooClient
    mod.client = client_mod
    monkeypatch.setitem(sys.modules, "kazoo", mod)
    monkeypatch.setitem(sys.modules, "kazoo.client", client_mod)
    FakeKazooClient.tree = {
        "zebra": {"0": [3, 1], "1": [1, 2]},
        "alpha": {"0": [1, 2], "10": [2, 3], "9": [3, 2]},
    }
    FakeKazooClient.fail_topic = None
    FakeKazooClient.started = []
    return FakeKazooClient


def test_zk_happy_path_walk_and_ordering(fake_kazoo):
    pl = get_partition_list_from_zookeeper("zk1:2181,zk2:2181/kafka")
    # chroot rides the hosts string (kazoo-go semantics)
    assert fake_kazoo.started == ["zk1:2181,zk2:2181/kafka"]
    got = [(p.topic, p.partition, p.replicas) for p in pl.iter_partitions()]
    # topics sorted lexically; partitions sorted NUMERICALLY (9 before 10)
    assert got == [
        ("alpha", 0, [1, 2]),
        ("alpha", 9, [3, 2]),
        ("alpha", 10, [2, 3]),
        ("zebra", 0, [3, 1]),
        ("zebra", 1, [1, 2]),
    ]
    # enrichment left unset like the reference's TODO (codecs.go:128-129)
    for p in pl.iter_partitions():
        assert p.weight == 0.0 and p.num_consumers == 0.0


def test_zk_topic_filter(fake_kazoo):
    pl = get_partition_list_from_zookeeper("zk1:2181", topics=["zebra"])
    assert {p.topic for p in pl.iter_partitions()} == {"zebra"}
    assert len(pl) == 2


def test_zk_midwalk_failure_maps_to_codec_error(fake_kazoo):
    fake_kazoo.fail_topic = "zebra"
    with pytest.raises(CodecError, match="topic zebra"):
        get_partition_list_from_zookeeper("zk1:2181")


def test_zk_cli_end_to_end(fake_kazoo):
    """-from-zk through run(): full pipeline on the fake cluster."""
    from kafkabalancer_tpu.cli import run

    out, err = io.StringIO(), io.StringIO()
    rv = run(
        io.StringIO(""), out, err,
        ["kafkabalancer", "-from-zk=zk1:2181", "-max-reassign=1"],
    )
    assert rv == 0, err.getvalue()
    plan = json.loads(out.getvalue())
    assert plan["version"] == 1


def test_zk_cli_error_paths_unchanged(fake_kazoo):
    from kafkabalancer_tpu.cli import run

    out, err = io.StringIO(), io.StringIO()
    rv = run(io.StringIO(""), out, err, ["kafkabalancer", "-from-zk=."])
    assert rv == 2
    assert "failed parsing zk connection string" in err.getvalue()
